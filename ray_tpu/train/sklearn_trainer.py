"""SklearnTrainer — single-worker scikit-learn fit on the train infra.

Reference: python/ray/train/sklearn/sklearn_trainer.py (`SklearnTrainer`:
fits an estimator in one remote worker, optionally cross-validating with
a joblib parallel backend over Ray, reports scores, and checkpoints the
pickled estimator). Same shape here: the fit runs inside one
RayTrainWorker actor via DataParallelTrainer(num_workers=1), CV
parallelism rides `ray_tpu.util.joblib.register_ray()`.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.air import Result, RunConfig, ScalingConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

MODEL_FILENAME = "model.pkl"


def _resolve_xy(data: Any, label_column: Optional[str]):
    """Dataset | pandas.DataFrame | (X, y) | X  →  (X, y|None)."""
    import numpy as np

    if isinstance(data, tuple) and len(data) == 2:
        return data
    if hasattr(data, "to_pandas"):  # ray_tpu.data.Dataset
        data = data.to_pandas()
    if hasattr(data, "drop"):  # pandas DataFrame
        if label_column is not None:
            y = data[label_column].to_numpy()
            X = data.drop(columns=[label_column]).to_numpy()
            return X, y
        return data.to_numpy(), None
    return np.asarray(data), None


def _sklearn_fit_loop(config: Dict[str, Any]) -> None:
    from ray_tpu import train

    estimator = config["estimator"]
    label_column = config.get("label_column")
    params = config.get("params") or {}
    scoring = config.get("scoring")
    cv = config.get("cv")
    parallelize_cv = config.get("parallelize_cv", False)
    datasets = config.get("_datasets") or {}

    if params:
        estimator = estimator.set_params(**params)

    X_train, y_train = _resolve_xy(datasets["train"], label_column)

    start = time.perf_counter()
    estimator.fit(X_train, y_train)
    metrics: Dict[str, Any] = {
        "fit_time": time.perf_counter() - start}

    def _score(X, y) -> float:
        if callable(scoring):
            return float(scoring(estimator, X, y))
        if isinstance(scoring, str):
            from sklearn.metrics import check_scoring

            return float(check_scoring(estimator, scoring)(estimator, X, y))
        return float(estimator.score(X, y))

    for name, data in datasets.items():
        if name == "train":
            continue
        X, y = _resolve_xy(data, label_column)
        metrics[f"{name}_score"] = _score(X, y)

    if cv:
        from sklearn.model_selection import cross_validate

        cv_scoring = scoring if isinstance(scoring, str) or \
            callable(scoring) else None
        if parallelize_cv:
            import joblib

            from ray_tpu.util.joblib import register_ray

            register_ray()
            with joblib.parallel_backend("ray_tpu"):
                cv_res = cross_validate(estimator, X_train, y_train,
                                        cv=cv, n_jobs=cv,
                                        scoring=cv_scoring)
        else:
            cv_res = cross_validate(estimator, X_train, y_train, cv=cv,
                                    scoring=cv_scoring)
        scores = cv_res["test_score"]
        metrics["cv_test_score_mean"] = float(scores.mean())
        metrics["cv_test_score_std"] = float(scores.std())

    d = tempfile.mkdtemp(prefix="sklearn_ckpt_")
    with open(os.path.join(d, MODEL_FILENAME), "wb") as f:
        pickle.dump(estimator, f)
    train.report(metrics, checkpoint=Checkpoint.from_directory(d))


class SklearnTrainer:
    def __init__(self, *,
                 estimator: Any,
                 datasets: Dict[str, Any],
                 label_column: Optional[str] = None,
                 params: Optional[Dict[str, Any]] = None,
                 scoring: Optional[Union[str, Callable]] = None,
                 cv: Optional[int] = None,
                 parallelize_cv: bool = False,
                 run_config: Optional[RunConfig] = None):
        if "train" not in datasets:
            raise ValueError("datasets must contain a 'train' key")
        self._inner = DataParallelTrainer(
            _sklearn_fit_loop,
            train_loop_config={
                "estimator": estimator,
                "label_column": label_column,
                "params": params,
                "scoring": scoring,
                "cv": cv,
                "parallelize_cv": parallelize_cv,
            },
            scaling_config=ScalingConfig(num_workers=1),
            run_config=run_config,
            datasets=datasets,
        )

    def fit(self) -> Result:
        return self._inner.fit()

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Unpickle the fitted estimator from a SklearnTrainer checkpoint
        (reference: train/sklearn/sklearn_checkpoint.py `get_model`)."""
        d = checkpoint.to_directory()
        with open(os.path.join(d, MODEL_FILENAME), "rb") as f:
            return pickle.load(f)
