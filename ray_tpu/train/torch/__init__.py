"""ray_tpu.train.torch — torch (CPU/gloo) trainer for API parity.

Reference: python/ray/train/torch/. The TPU path is JaxTrainer; this
package lets reference users run existing torch train loops unchanged.
"""

from ray_tpu.train.torch.config import TorchConfig
from ray_tpu.train.torch.torch_trainer import (TorchTrainer, prepare_model,
                                               prepare_data_loader)

__all__ = ["TorchConfig", "TorchTrainer", "prepare_model",
           "prepare_data_loader"]
