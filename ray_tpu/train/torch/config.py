"""TorchConfig / _TorchBackend — torch.distributed process groups.

Reference: python/ray/train/torch/config.py:150 (`_TorchBackend.on_start`
→ `_setup_torch_process_group` :65): rank 0 hosts the TCP store, every
worker joins init_process_group. Torch here is CPU/gloo — the TPU compute
path is JAX (JaxTrainer); TorchTrainer exists for CPU workloads and API
parity so reference users can bring torch train loops unchanged.
"""

from __future__ import annotations

import dataclasses
import datetime
import os

from ray_tpu.train.backend import Backend, BackendConfig


@dataclasses.dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_method: str = "env"
    timeout_s: int = 1800

    @property
    def backend_cls(self):
        return _TorchBackend


def _setup_torch_process_group(backend: str, world_rank: int,
                               world_size: int, init_method: str,
                               master_addr: str, master_port: int,
                               timeout_s: int) -> bool:
    import torch.distributed as dist

    if dist.is_initialized():
        return True
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(world_rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    if init_method == "env":
        url = "env://"
    elif init_method == "tcp":
        url = f"tcp://{master_addr}:{master_port}"
    else:
        raise ValueError(f"unknown init_method {init_method!r}")
    dist.init_process_group(
        backend=backend, init_method=url, rank=world_rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))
    return True


def _shutdown_torch() -> None:
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig) -> None:
        if len(worker_group) <= 1:
            return
        import ray_tpu

        infos = worker_group.execute("get_node_info")
        master_addr = infos[0]["ip"]
        master_port = infos[0]["free_port"]
        refs = [
            w.run_fn.remote(_setup_torch_process_group,
                            backend_config.backend, rank,
                            len(worker_group), backend_config.init_method,
                            master_addr, master_port,
                            backend_config.timeout_s)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs)

    def on_shutdown(self, worker_group, backend_config) -> None:
        import ray_tpu

        try:
            ray_tpu.get([w.run_fn.remote(_shutdown_torch)
                         for w in worker_group.workers])
        except Exception:
            pass
