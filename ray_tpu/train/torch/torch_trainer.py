"""TorchTrainer + train-loop utilities (DDP prep).

Reference: python/ray/train/torch/torch_trainer.py:11 (TorchTrainer) and
train_loop_utils.py:158/:200 (prepare_model DDP wrap, prepare_data_loader
DistributedSampler). CPU/gloo here — TPU training is JaxTrainer.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.torch.config import TorchConfig


class TorchTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker, *,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=torch_config or TorchConfig(),
                         **kwargs)


def prepare_model(model, parallel_strategy: Optional[str] = "ddp"):
    """Wrap in DDP when a process group is live (reference:
    train_loop_utils.py:158). parallel_strategy None returns the model
    unwrapped (fsdp is torch-GPU territory; on TPU use JaxTrainer)."""
    import torch.distributed as dist

    if parallel_strategy is None or not dist.is_initialized() or \
            dist.get_world_size() <= 1:
        return model
    if parallel_strategy == "ddp":
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    raise ValueError(
        f"parallel_strategy {parallel_strategy!r} not supported here "
        "(fsdp requires GPU; TPU sharding lives in JaxTrainer/GSPMD)")


def prepare_data_loader(data_loader):
    """Re-wrap a DataLoader with a DistributedSampler (reference:
    train_loop_utils.py:200)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return data_loader
    # Preserve the loader's ordering semantics: only shuffle if the
    # original sampler shuffled (eval loaders must stay ordered).
    shuffle = isinstance(data_loader.sampler, RandomSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffle)
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last)
