// transfer — native cross-node object streaming (object-manager data plane).
//
// TPU-native counterpart of the reference's chunked object push/pull
// (src/ray/object_manager/object_manager.cc + object_buffer_pool.h): the
// bulk bytes of an object move store-to-store over a raw TCP socket with
// zero Python on the data path — the sender streams straight out of its
// mapped shm arena, the receiver recv()s straight into a pinned allocation
// in its own arena and seals it. Python (the raylet) only decides WHAT to
// fetch from WHERE; the bytes never enter the interpreter.
//
// Protocol (one object per connection, receiver-initiated pull):
//   request : u64 magic | u8 id[kIdSize=24]
//   response: u32 status (0=ok, 1=not found) | u64 size | payload bytes
//
// Build: compiled together with shm_store.cpp into libray_tpu_transfer.so.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <string>
#include <unordered_map>

// From shm_store.cpp (same shared library).
extern "C" {
void* shm_store_open(const char* path);
void shm_store_close(void* handle);
int shm_create(void* handle, const uint8_t* id, uint64_t size,
               uint64_t* out_offset);
int shm_seal(void* handle, const uint8_t* id);
int shm_abort(void* handle, const uint8_t* id);
int shm_get(void* handle, const uint8_t* id, long timeout_ms,
            uint64_t* out_offset, uint64_t* out_size);
int shm_release(void* handle, const uint8_t* id);
uint8_t* shm_data_pointer(void* handle, uint64_t offset);
}

namespace {

constexpr uint64_t kReqMagic = 0x5452414E53464552ULL;  // "TRANSFER"
// Matches shm_store.cpp kIdSize (= Python ObjectID.SIZE = 24).
constexpr int kIdSize = 24;

// Bound every socket op: a stalled peer must fail the pull so the
// caller can fall back to the rpc path (which carries its own timeouts).
constexpr int kIoTimeoutSec = 30;

void set_io_timeouts(int fd) {
  timeval tv{kIoTimeoutSec, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

bool read_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

struct Server {
  void* store = nullptr;
  int listen_fd = -1;
  std::atomic<bool> stop{false};
  std::atomic<int> active_handlers{0};
  pthread_t thread{};
  // Live connection fds so stop() can force-close in-flight transfers.
  pthread_mutex_t conn_mu = PTHREAD_MUTEX_INITIALIZER;
  std::unordered_map<int, int> conn_fds;

  void track(int fd) {
    pthread_mutex_lock(&conn_mu);
    conn_fds[fd] = fd;
    pthread_mutex_unlock(&conn_mu);
  }
  void untrack(int fd) {
    pthread_mutex_lock(&conn_mu);
    conn_fds.erase(fd);
    pthread_mutex_unlock(&conn_mu);
  }
  void shutdown_all() {
    pthread_mutex_lock(&conn_mu);
    for (auto& kv : conn_fds) shutdown(kv.first, SHUT_RDWR);
    pthread_mutex_unlock(&conn_mu);
  }
};

// Client-side store handles are opened once per (process, path) and kept
// for the process lifetime: a pull must not pay mmap/munmap per object.
pthread_mutex_t g_client_stores_mu = PTHREAD_MUTEX_INITIALIZER;
std::unordered_map<std::string, void*>* g_client_stores = nullptr;

void* client_store(const char* path) {
  pthread_mutex_lock(&g_client_stores_mu);
  if (!g_client_stores) {
    g_client_stores = new std::unordered_map<std::string, void*>();
  }
  auto it = g_client_stores->find(path);
  void* handle = (it != g_client_stores->end()) ? it->second : nullptr;
  if (!handle) {
    handle = shm_store_open(path);
    if (handle) (*g_client_stores)[path] = handle;
  }
  pthread_mutex_unlock(&g_client_stores_mu);
  return handle;
}

struct ConnTask {
  Server* server;
  int fd;
};

void* handle_conn(void* arg) {
  ConnTask* task = static_cast<ConnTask*>(arg);
  int fd = task->fd;
  Server* server = task->server;
  delete task;
  // active_handlers was incremented by the accept loop BEFORE spawning
  // us; obj_transfer_stop waits for it to drain before freeing server.
  server->track(fd);
  struct Guard {
    Server* s;
    int fd;
    ~Guard() {
      s->untrack(fd);
      s->active_handlers.fetch_sub(1);
    }
  } guard{server, fd};

  uint64_t magic = 0;
  uint8_t id[kIdSize];
  if (!read_exact(fd, &magic, sizeof(magic)) || magic != kReqMagic ||
      !read_exact(fd, id, kIdSize)) {
    close(fd);
    return nullptr;
  }
  uint64_t offset = 0, size = 0;
  int rc = shm_get(server->store, id, /*timeout_ms=*/0, &offset, &size);
  uint32_t status = (rc == 0) ? 0u : 1u;
  uint64_t send_size = (rc == 0) ? size : 0;
  if (!write_exact(fd, &status, sizeof(status)) ||
      !write_exact(fd, &send_size, sizeof(send_size))) {
    if (rc == 0) shm_release(server->store, id);
    close(fd);
    return nullptr;
  }
  if (rc == 0) {
    const uint8_t* data = shm_data_pointer(server->store, offset);
    write_exact(fd, data, size);
    shm_release(server->store, id);
  }
  close(fd);
  return nullptr;
}

void* accept_loop(void* arg) {
  Server* server = static_cast<Server*>(arg);
  while (!server->stop.load()) {
    int fd = accept(server->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (server->stop.load()) break;
      if (errno == EINTR) continue;
      break;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    set_io_timeouts(fd);
    pthread_t t;
    ConnTask* task = new ConnTask{server, fd};
    server->active_handlers.fetch_add(1);
    if (pthread_create(&t, nullptr, handle_conn, task) == 0) {
      pthread_detach(t);
    } else {
      server->active_handlers.fetch_sub(1);
      delete task;
      close(fd);
    }
  }
  return nullptr;
}

}  // namespace

extern "C" {

// Start serving objects from the store at `store_path`. Returns the bound
// port (>0) or -errno. `out_server` receives an opaque server handle.
int obj_transfer_serve(const char* store_path, void** out_server) {
  void* store = shm_store_open(store_path);
  if (!store) return -EINVAL;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    shm_store_close(store);
    return -errno;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    int e = errno;
    close(fd);
    shm_store_close(store);
    return -e;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);

  Server* server = new Server();
  server->store = store;
  server->listen_fd = fd;
  if (pthread_create(&server->thread, nullptr, accept_loop, server) != 0) {
    close(fd);
    shm_store_close(store);
    delete server;
    return -EAGAIN;
  }
  *out_server = server;
  return ntohs(addr.sin_port);
}

void obj_transfer_stop(void* server_ptr) {
  Server* server = static_cast<Server*>(server_ptr);
  server->stop.store(true);
  shutdown(server->listen_fd, SHUT_RDWR);
  close(server->listen_fd);
  pthread_join(server->thread, nullptr);
  // Force in-flight transfers to fail fast, then wait for handlers to
  // drain before freeing the store they read from.
  server->shutdown_all();
  bool drained = false;
  for (int i = 0; i < 500; i++) {  // ~5s; IO fails immediately after
                                   // shutdown so this is generous
    if (server->active_handlers.load() == 0) {
      drained = true;
      break;
    }
    usleep(10 * 1000);
  }
  if (!drained) {
    // A handler is wedged beyond reason: leak the server rather than
    // free memory it still dereferences (shutdown path only).
    return;
  }
  shm_store_close(server->store);
  delete server;
}

// Pull object `id` from host:port straight into the store at `store_path`.
// Returns 0 ok, 1 remote miss, 2 local exists (fine), -errno on I/O error.
int obj_transfer_fetch(const char* store_path, const char* host, int port,
                       const uint8_t* id) {
  void* store = client_store(store_path);  // cached per-process handle
  if (!store) return -EINVAL;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  set_io_timeouts(fd);  // SO_SNDTIMEO also bounds connect() on Linux
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  int result = -EIO;
  uint64_t offset = 0;
  bool created = false;
  do {
    if (!write_exact(fd, &kReqMagic, sizeof(kReqMagic)) ||
        !write_exact(fd, id, kIdSize)) break;
    uint32_t status = 0;
    uint64_t size = 0;
    if (!read_exact(fd, &status, sizeof(status)) ||
        !read_exact(fd, &size, sizeof(size))) break;
    if (status != 0) {
      result = 1;  // remote miss
      break;
    }
    int rc = shm_create(store, id, size, &offset);
    if (rc == -1 /*ERR_EXISTS*/) {
      result = 2;
      break;
    }
    if (rc != 0) {
      result = -ENOSPC;
      break;
    }
    created = true;
    uint8_t* dst = shm_data_pointer(store, offset);
    if (!read_exact(fd, dst, size)) break;
    if (shm_seal(store, id) != 0) break;
    created = false;  // sealed — no abort needed
    result = 0;
  } while (false);
  if (created) shm_abort(store, id);
  close(fd);
  return result;
}

}  // extern "C"
