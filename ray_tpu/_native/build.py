"""Build the native components on demand (g++ → .so, cached by mtime).

The reference ships prebuilt native artifacts via Bazel (BUILD.bazel →
_raylet.so, raylet, gcs_server); here the native library is compiled once at
first import and cached under _native/build/.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "build")
_LOCK = threading.Lock()

_LIBS = {
    "ray_tpu_store": ["shm_store.cpp"],
    "ray_tpu_transfer": ["shm_store.cpp", "transfer.cpp"],
    "ray_tpu_channel": ["mutable_channel.cpp"],
    "ray_tpu_fastlane": ["fastlane.cpp"],
}


def lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def ensure_built(name: str, force: bool = False) -> str:
    """Compile lib<name>.so if missing or stale; return its path."""
    sources = [os.path.join(_DIR, s) for s in _LIBS[name]]
    out = lib_path(name)
    with _LOCK:
        if not force and os.path.exists(out):
            src_mtime = max(os.path.getmtime(s) for s in sources)
            if os.path.getmtime(out) >= src_mtime:
                return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = out + ".tmp"
        cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-Wall",
               "-o", tmp] + sources + ["-lpthread"]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    return out


def load_lib(name: str):
    """ensure_built + ctypes.CDLL, recompiling once if the cached .so fails
    to load (e.g. an artifact built on a different platform/glibc)."""
    import ctypes

    try:
        return ctypes.CDLL(ensure_built(name))
    except OSError:
        return ctypes.CDLL(ensure_built(name, force=True))
