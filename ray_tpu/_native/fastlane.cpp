// Fastlane: GIL-free framed request/reply transport for the task hot path.
//
// TPU-native counterpart of the reference's C++ rpc layer on the task
// submission/execution path (src/ray/rpc/server_call.h,
// src/ray/core_worker/transport/normal_task_submitter.cc:24): message
// framing, request/reply correlation, and the submit/receive pump live in
// native threads; Python supplies only policy (what to execute, how to
// store results). All blocking entry points are plain C functions called
// through ctypes, so the GIL is dropped while a thread sits in a send,
// a reply wait, or the server's request queue.
//
// Wire format (both directions): [u32 little-endian payload len]
// [u64 little-endian msgid][payload bytes]. A client opens a TCP
// connection and sends the 8-byte magic "FLNLANE1" before the first
// frame; the server validates it.
//
// Ordering contract: the server delivers at most ONE outstanding request
// per connection to Python; the next frame from that connection is
// delivered only after the previous one was replied to. This preserves
// per-caller FIFO execution (the reference's actor scheduling queues)
// while letting independent callers proceed in parallel.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr char kMagic[8] = {'F', 'L', 'N', 'L', 'A', 'N', 'E', '1'};

struct Frame {
  uint64_t msgid;
  char* data;      // malloc'd; ownership passes to the consumer
  int64_t len;
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r > 0) {
      p += r;
      n -= static_cast<size_t>(r);
    } else if (r == 0) {
      return false;  // EOF
    } else if (errno == EINTR) {
      continue;
    } else {
      return false;
    }
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r > 0) {
      p += r;
      n -= static_cast<size_t>(r);
    } else if (r < 0 && errno == EINTR) {
      continue;
    } else {
      return false;
    }
  }
  return true;
}

bool read_frame(int fd, Frame* out) {
  unsigned char hdr[12];
  if (!read_exact(fd, hdr, sizeof(hdr))) return false;
  uint32_t len;
  uint64_t msgid;
  memcpy(&len, hdr, 4);
  memcpy(&msgid, hdr + 4, 8);
  if (len > (1u << 30)) return false;  // corrupt / hostile length
  char* data = static_cast<char*>(malloc(len ? len : 1));
  if (data == nullptr) return false;
  if (!read_exact(fd, data, len)) {
    free(data);
    return false;
  }
  out->msgid = msgid;
  out->data = data;
  out->len = len;
  return true;
}

bool write_frame(int fd, uint64_t msgid, const char* buf, int64_t len) {
  unsigned char hdr[12];
  uint32_t l = static_cast<uint32_t>(len);
  memcpy(hdr, &l, 4);
  memcpy(hdr + 4, &msgid, 8);
  // One writev so a small frame hits the wire in a single segment.
  struct iovec iov[2];
  iov[0].iov_base = hdr;
  iov[0].iov_len = sizeof(hdr);
  iov[1].iov_base = const_cast<char*>(buf);
  iov[1].iov_len = static_cast<size_t>(len);
  size_t total = sizeof(hdr) + static_cast<size_t>(len);
  size_t done = 0;
  while (done < total) {
    ssize_t r = ::writev(fd, iov, 2);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    done += static_cast<size_t>(r);
    if (done >= total) break;
    // Partial write: rebuild the iov view (rare; small frames).
    size_t skip = done;
    if (skip < sizeof(hdr)) {
      iov[0].iov_base = hdr + skip;
      iov[0].iov_len = sizeof(hdr) - skip;
      iov[1].iov_base = const_cast<char*>(buf);
      iov[1].iov_len = static_cast<size_t>(len);
    } else {
      iov[0].iov_base = hdr;
      iov[0].iov_len = 0;
      iov[1].iov_base = const_cast<char*>(buf) + (skip - sizeof(hdr));
      iov[1].iov_len = static_cast<size_t>(len) - (skip - sizeof(hdr));
    }
  }
  return true;
}

// ------------------------------------------------------------------ client

struct Client {
  int fd = -1;
  std::mutex write_mu;
  std::mutex mu;  // guards replies/closed
  std::condition_variable cv;
  std::deque<Frame> replies;
  bool closed = false;
  std::thread reader;

  ~Client() {
    for (auto& f : replies) free(f.data);
  }
};

void client_reader(Client* c) {
  for (;;) {
    Frame f;
    if (!read_frame(c->fd, &f)) break;
    std::lock_guard<std::mutex> lk(c->mu);
    c->replies.push_back(f);
    c->cv.notify_all();
  }
  std::lock_guard<std::mutex> lk(c->mu);
  c->closed = true;
  c->cv.notify_all();
}

// ------------------------------------------------------------------ server

struct ServerConn {
  int fd = -1;
  uint64_t id = 0;
  std::mutex write_mu;
  std::thread reader;
  // Guarded by the owning server's mu:
  std::deque<Frame> backlog;
  bool in_flight = false;
  bool alive = true;
};

struct Request {
  uint64_t reqid;
  Frame frame;
};

struct Server {
  int listen_fd = -1;
  std::thread acceptor;
  std::mutex mu;  // guards everything below
  std::condition_variable cv;
  std::deque<Request> ready;
  std::unordered_map<uint64_t, std::shared_ptr<ServerConn>> conns;
  // reqid -> (conn id, wire msgid)
  std::unordered_map<uint64_t, std::pair<uint64_t, uint64_t>> outstanding;
  uint64_t next_conn_id = 1;
  uint64_t next_reqid = 1;
  bool closed = false;
  std::vector<std::thread> reapers;  // finished conn reader threads
};

void conn_reader(Server* s, std::shared_ptr<ServerConn> c) {
  char magic[8];
  if (read_exact(c->fd, magic, 8) && memcmp(magic, kMagic, 8) == 0) {
    for (;;) {
      Frame f;
      if (!read_frame(c->fd, &f)) break;
      std::lock_guard<std::mutex> lk(s->mu);
      if (s->closed) {
        free(f.data);
        break;
      }
      if (c->in_flight) {
        c->backlog.push_back(f);
      } else {
        c->in_flight = true;
        uint64_t reqid = s->next_reqid++;
        s->outstanding[reqid] = {c->id, f.msgid};
        s->ready.push_back({reqid, f});
        s->cv.notify_one();
      }
    }
  }
  // Connection gone: drop its backlog; outstanding entries become
  // no-op replies.
  std::lock_guard<std::mutex> lk(s->mu);
  c->alive = false;
  for (auto& f : c->backlog) free(f.data);
  c->backlog.clear();
  ::close(c->fd);
  s->conns.erase(c->id);
}

void acceptor_loop(Server* s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listener closed
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto c = std::make_shared<ServerConn>();
    c->fd = fd;
    std::lock_guard<std::mutex> lk(s->mu);
    if (s->closed) {
      ::close(fd);
      break;
    }
    c->id = s->next_conn_id++;
    s->conns[c->id] = c;
    c->reader = std::thread(conn_reader, s, c);
    c->reader.detach();
  }
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- client API

void* fl_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  // Bounded connect: temporary SO_SNDTIMEO-free approach via non-block +
  // poll would be longer; the listener is local so a plain connect with
  // a receive timeout is enough in practice.
  struct timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  if (::connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
                sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  tv.tv_sec = 0;
  tv.tv_usec = 0;
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (!write_exact(fd, kMagic, 8)) {
    ::close(fd);
    return nullptr;
  }
  Client* c = new Client();
  c->fd = fd;
  c->reader = std::thread(client_reader, c);
  return c;
}

// Send one request frame. msgid is caller-assigned (register your
// completion BEFORE calling, so a fast reply can't race the bookkeeping).
// Returns 0 on success, -1 on a dead connection.
int fl_send(void* h, uint64_t msgid, const char* buf, int64_t len) {
  Client* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->write_mu);
  if (!write_frame(c->fd, msgid, buf, len)) {
    ::shutdown(c->fd, SHUT_RDWR);
    return -1;
  }
  return 0;
}

// Wait for any reply. Returns msgid (>0) with *out/*outlen set (caller
// frees via fl_buf_free), 0 on timeout, -1 when the connection is closed
// and no replies remain.
int64_t fl_wait_any(void* h, int timeout_ms, char** out, int64_t* outlen) {
  Client* c = static_cast<Client*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  if (c->replies.empty()) {
    c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
      return !c->replies.empty() || c->closed;
    });
  }
  if (!c->replies.empty()) {
    Frame f = c->replies.front();
    c->replies.pop_front();
    *out = f.data;
    *outlen = f.len;
    return static_cast<int64_t>(f.msgid);
  }
  return c->closed ? -1 : 0;
}

int fl_closed(void* h) {
  Client* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->closed ? 1 : 0;
}

// Wake the reader and fail future sends WITHOUT freeing: lets another
// thread blocked in fl_wait_any observe closure (-1) and perform the
// final fl_close itself, avoiding a use-after-free on the handle.
void fl_shutdown(void* h) {
  Client* c = static_cast<Client*>(h);
  ::shutdown(c->fd, SHUT_RDWR);
}

void fl_close(void* h) {
  Client* c = static_cast<Client*>(h);
  ::shutdown(c->fd, SHUT_RDWR);
  if (c->reader.joinable()) c->reader.join();
  ::close(c->fd);
  delete c;
}

void fl_buf_free(char* buf) { free(buf); }

// ---------------------------------------------------------------- server API

void* fl_server_create(int* port_out) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  if (::bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) !=
          0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<struct sockaddr*>(&addr), &alen);
  *port_out = ntohs(addr.sin_port);
  Server* s = new Server();
  s->listen_fd = fd;
  s->acceptor = std::thread(acceptor_loop, s);
  return s;
}

// Pop the next request. Returns reqid (>0) with *out/*outlen set (caller
// frees via fl_buf_free), 0 on timeout, -1 when the server is closed.
int64_t fl_server_next(void* h, int timeout_ms, char** out,
                       int64_t* outlen) {
  Server* s = static_cast<Server*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  if (s->ready.empty()) {
    s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
      return !s->ready.empty() || s->closed;
    });
  }
  if (!s->ready.empty()) {
    Request r = s->ready.front();
    s->ready.pop_front();
    *out = r.frame.data;
    *outlen = r.frame.len;
    return static_cast<int64_t>(r.reqid);
  }
  return s->closed ? -1 : 0;
}

// Reply to a request and release the connection's FIFO gate (queueing its
// next backlogged frame, if any). Returns 0; a dead peer is a no-op.
int fl_server_reply(void* h, uint64_t reqid, const char* buf, int64_t len) {
  Server* s = static_cast<Server*>(h);
  std::shared_ptr<ServerConn> c;
  uint64_t wire_msgid = 0;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->outstanding.find(reqid);
    if (it == s->outstanding.end()) return 0;
    uint64_t conn_id = it->second.first;
    wire_msgid = it->second.second;
    s->outstanding.erase(it);
    auto cit = s->conns.find(conn_id);
    if (cit == s->conns.end()) return 0;  // peer died meanwhile
    c = cit->second;
  }
  {
    std::lock_guard<std::mutex> wlk(c->write_mu);
    if (!write_frame(c->fd, wire_msgid, buf, len)) {
      ::shutdown(c->fd, SHUT_RDWR);
    }
  }
  std::lock_guard<std::mutex> lk(s->mu);
  if (!c->alive) return 0;
  if (!c->backlog.empty()) {
    Frame f = c->backlog.front();
    c->backlog.pop_front();
    uint64_t next_reqid = s->next_reqid++;
    s->outstanding[next_reqid] = {c->id, f.msgid};
    s->ready.push_back({next_reqid, f});
    s->cv.notify_one();
  } else {
    c->in_flight = false;
  }
  return 0;
}

// Stop accepting and wake every fl_server_next caller (they observe -1)
// WITHOUT freeing the handle; call fl_server_close only after all
// dispatcher threads have exited.
void fl_server_shutdown(void* h) {
  Server* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    if (s->closed) return;
    s->closed = true;
    s->cv.notify_all();
    for (auto& kv : s->conns) ::shutdown(kv.second->fd, SHUT_RDWR);
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
}

void fl_server_close(void* h) {
  Server* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->closed = true;
    s->cv.notify_all();
    for (auto& kv : s->conns) ::shutdown(kv.second->fd, SHUT_RDWR);
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->acceptor.joinable()) s->acceptor.join();
  // Wait for detached conn readers to drain (they touch s->mu / s->conns
  // on their way out). If one is still wedged after the grace period,
  // intentionally LEAK the server instead of freeing memory a reader may
  // still lock — this only runs at process shutdown.
  bool drained = false;
  for (int i = 0; i < 500; ++i) {
    {
      std::lock_guard<std::mutex> lk(s->mu);
      if (s->conns.empty()) {
        drained = true;
        break;
      }
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  if (!drained) return;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    for (auto& r : s->ready) free(r.frame.data);
    s->ready.clear();
  }
  delete s;
}

}  // extern "C"
