// mutable_channel — preallocated mutable shared-memory channels for
// compiled DAGs.
//
// TPU-native counterpart of the reference's experimental mutable objects
// (src/ray/core_worker/experimental_mutable_object_manager.h, python side
// python/ray/experimental/channel/shared_memory_channel.py:147): a channel
// is a fixed-capacity shared-memory ring (2..64 slots) written in place
// by ONE producer and read by up to kMaxReaders consumers, with
// sequence-number publication under a robust process-shared mutex+condvar.
// A steady-state compiled-DAG pipeline moves data purely through these
// segments: zero RPCs, zero allocations, one memcpy per hop.
//
// Protocol (seq starts at 0 = nothing published):
//   writer publishes seq X into slot X%n_slots; overwriting that slot
//   destroys seq X-n_slots, so the writer waits until
//   min(read_seq) >= X-n_slots. reader r consumes sequences in order:
//   next = read_seq[r]+1, valid while the reader holds it (release sets
//   read_seq[r] = next, letting the writer advance).
//
// Build: part of libray_tpu_channel.so (see _native/build.py).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5241595f4348414eULL;  // "RAY_CHAN"
constexpr uint32_t kVersion = 1;
constexpr uint32_t kMaxReaders = 16;
constexpr uint32_t kMaxSlots = 64;
constexpr uint64_t kAlign = 64;

enum Status : int {
  OK = 0,
  ERR_TIMEOUT = -4,
  ERR_INVALID = -5,
  ERR_CLOSED = -8,
  ERR_TOO_LARGE = -9,
};

struct ChanHeader {
  uint64_t magic;
  uint32_t version;
  uint32_t n_readers;
  uint32_t n_slots;
  uint32_t closed;
  uint64_t slot_capacity;
  uint64_t data_start;          // file offset of slot 0; slots follow
  uint64_t write_seq;           // last published sequence
  uint64_t len[kMaxSlots];      // payload length per slot
  uint64_t read_seq[kMaxReaders];
  pthread_mutex_t mutex;
  pthread_cond_t cond;
};

struct ChanHandle {
  int fd;
  uint8_t* base;
  uint64_t map_size;
  ChanHeader* hdr;
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

int lock(ChanHeader* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}

inline void unlock(ChanHeader* h) { pthread_mutex_unlock(&h->mutex); }

void monotonic_deadline(struct timespec* ts, long timeout_ms) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// Wait on the condvar until pred holds, the channel closes, or timeout.
// Returns OK, ERR_TIMEOUT, or ERR_CLOSED (checked by caller via pred —
// this helper only times the wait). Mutex must be held.
template <typename Pred>
int wait_for(ChanHeader* h, Pred pred, long timeout_ms) {
  struct timespec deadline;
  if (timeout_ms >= 0) monotonic_deadline(&deadline, timeout_ms);
  while (!pred()) {
    int rc;
    if (timeout_ms >= 0) {
      rc = pthread_cond_timedwait(&h->cond, &h->mutex, &deadline);
    } else {
      rc = pthread_cond_wait(&h->cond, &h->mutex);
    }
    if (rc == ETIMEDOUT) return pred() ? OK : ERR_TIMEOUT;
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mutex);
  }
  return OK;
}

uint64_t min_read_seq(ChanHeader* h) {
  uint64_t m = UINT64_MAX;
  for (uint32_t i = 0; i < h->n_readers; i++) {
    if (h->read_seq[i] < m) m = h->read_seq[i];
  }
  return h->n_readers ? m : h->write_seq;
}

}  // namespace

extern "C" {

int chan_create(const char* path, uint64_t slot_capacity,
                uint32_t n_readers, uint32_t n_slots) {
  if (n_readers == 0 || n_readers > kMaxReaders) return ERR_INVALID;
  if (n_slots < 2 || n_slots > kMaxSlots) return ERR_INVALID;
  uint64_t data_start = align_up(sizeof(ChanHeader));
  uint64_t total = data_start + n_slots * align_up(slot_capacity);
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return ERR_INVALID;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    unlink(path);
    return ERR_INVALID;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    unlink(path);
    return ERR_INVALID;
  }
  ChanHeader* h = static_cast<ChanHeader*>(base);
  memset(h, 0, sizeof(ChanHeader));
  h->version = kVersion;
  h->n_readers = n_readers;
  h->n_slots = n_slots;
  h->slot_capacity = align_up(slot_capacity);
  h->data_start = data_start;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&h->cond, &ca);
  pthread_condattr_destroy(&ca);

  h->magic = kMagic;  // last: publication barrier for openers
  msync(base, sizeof(ChanHeader), MS_SYNC);
  munmap(base, total);
  close(fd);
  return OK;
}

void* chan_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<uint64_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  ChanHeader* h = static_cast<ChanHeader*>(base);
  if (h->magic != kMagic || h->version != kVersion) {
    munmap(base, static_cast<uint64_t>(st.st_size));
    close(fd);
    return nullptr;
  }
  ChanHandle* ch = new ChanHandle;
  ch->fd = fd;
  ch->base = static_cast<uint8_t*>(base);
  ch->map_size = static_cast<uint64_t>(st.st_size);
  ch->hdr = h;
  return ch;
}

void chan_close_handle(void* handle) {
  ChanHandle* ch = static_cast<ChanHandle*>(handle);
  if (!ch) return;
  munmap(ch->base, ch->map_size);
  close(ch->fd);
  delete ch;
}

// Publish one value. Blocks until the target slot is reclaimable (all
// readers consumed seq-2) or timeout. timeout_ms < 0 = infinite.
int chan_write(void* handle, const uint8_t* data, uint64_t len,
               long timeout_ms) {
  ChanHandle* ch = static_cast<ChanHandle*>(handle);
  ChanHeader* h = ch->hdr;
  if (len > h->slot_capacity) return ERR_TOO_LARGE;
  lock(h);
  uint64_t next = h->write_seq + 1;
  uint64_t depth = h->n_slots;
  int rc = wait_for(
      h,
      [h, next, depth] {
        return h->closed || min_read_seq(h) + depth >= next;
      },
      timeout_ms);
  if (h->closed) {
    unlock(h);
    return ERR_CLOSED;
  }
  if (rc != OK) {
    unlock(h);
    return rc;
  }
  uint32_t slot = static_cast<uint32_t>(next % h->n_slots);
  uint8_t* dst = ch->base + h->data_start + slot * align_up(h->slot_capacity);
  // Copy under the lock: readers never touch an unpublished slot, but a
  // racing writer re-open must not interleave. Single-producer channels
  // make this uncontended in practice.
  memcpy(dst, data, len);
  h->len[slot] = len;
  h->write_seq = next;
  pthread_cond_broadcast(&h->cond);
  unlock(h);
  return OK;
}

// Acquire the next value for `reader`. On OK, *out_ptr/*out_len describe
// the payload, valid until chan_read_release. timeout_ms < 0 = infinite.
int chan_read_acquire(void* handle, uint32_t reader, uint8_t** out_ptr,
                      uint64_t* out_len, long timeout_ms) {
  ChanHandle* ch = static_cast<ChanHandle*>(handle);
  ChanHeader* h = ch->hdr;
  if (reader >= h->n_readers) return ERR_INVALID;
  lock(h);
  uint64_t next = h->read_seq[reader] + 1;
  int rc = wait_for(
      h, [h, next] { return h->closed || h->write_seq >= next; },
      timeout_ms);
  if (h->write_seq < next) {  // nothing left: closed or timeout
    uint32_t closed = h->closed;
    unlock(h);
    return closed ? ERR_CLOSED : (rc != OK ? rc : ERR_TIMEOUT);
  }
  uint32_t slot = static_cast<uint32_t>(next % h->n_slots);
  *out_ptr = ch->base + h->data_start + slot * align_up(h->slot_capacity);
  *out_len = h->len[slot];
  unlock(h);
  return OK;
}

int chan_read_release(void* handle, uint32_t reader) {
  ChanHandle* ch = static_cast<ChanHandle*>(handle);
  ChanHeader* h = ch->hdr;
  if (reader >= h->n_readers) return ERR_INVALID;
  lock(h);
  h->read_seq[reader] += 1;
  pthread_cond_broadcast(&h->cond);
  unlock(h);
  return OK;
}

// Mark closed and wake everyone. Readers drain remaining published values
// then get ERR_CLOSED; writes fail immediately.
int chan_close(void* handle) {
  ChanHandle* ch = static_cast<ChanHandle*>(handle);
  ChanHeader* h = ch->hdr;
  lock(h);
  h->closed = 1;
  pthread_cond_broadcast(&h->cond);
  unlock(h);
  return OK;
}

int chan_stats(void* handle, uint64_t* write_seq, uint64_t* min_read,
               uint32_t* closed) {
  ChanHandle* ch = static_cast<ChanHandle*>(handle);
  ChanHeader* h = ch->hdr;
  lock(h);
  *write_seq = h->write_seq;
  *min_read = min_read_seq(h);
  *closed = h->closed;
  unlock(h);
  return OK;
}

}  // extern "C"
