// Native-layer unit tests (SURVEY §4 test tier 1: the reference keeps
// 111 gtest files beside src/ray; this deployment has no gtest, so this
// is a dependency-free assert-style binary). It dlopens the SHIPPED
// .so artifacts (not a re-compile of the sources) so the bits under
// test are exactly the bits the Python bindings load — and so the two
// libraries' internal helpers (align_up, lock, ...) can't collide at
// link time.
//
// Driven by tests/test_native_units.py: builds via _native/build.py,
// compiles this file, runs it, asserts exit code 0.

#include <dlfcn.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,    \
                   #cond);                                            \
      return 1;                                                       \
    }                                                                 \
  } while (0)

// shm_store error codes (shm_store.cpp).
enum {
  S_OK = 0,
  S_EXISTS = -1,
  S_NOT_FOUND = -2,
  S_FULL = -3,
  S_TIMEOUT = -4,
  S_IN_USE = -7,
};
// mutable_channel error codes (mutable_channel.cpp).
enum {
  C_OK = 0,
  C_TIMEOUT = -4,
  C_INVALID = -5,
  C_CLOSED = -8,
  C_TOO_LARGE = -9,
};
constexpr int kIdSize = 24;

template <typename T>
static T sym(void* lib, const char* name) {
  void* p = dlsym(lib, name);
  if (!p) {
    std::fprintf(stderr, "missing symbol %s\n", name);
    std::abort();
  }
  return reinterpret_cast<T>(p);
}

static void make_id(uint8_t* id, uint8_t tag) {
  std::memset(id, 0, kIdSize);
  id[0] = tag;
  id[kIdSize - 1] = tag;
}

// ----------------------------------------------------------------- store

static int test_store(void* lib, const std::string& dir) {
  auto create = sym<int (*)(const char*, uint64_t, uint32_t)>(
      lib, "shm_store_create");
  auto open_ = sym<void* (*)(const char*)>(lib, "shm_store_open");
  auto close_ = sym<void (*)(void*)>(lib, "shm_store_close");
  auto obj_create = sym<int (*)(void*, const uint8_t*, uint64_t,
                                uint64_t*)>(lib, "shm_create");
  auto seal = sym<int (*)(void*, const uint8_t*)>(lib, "shm_seal");
  auto abort_ = sym<int (*)(void*, const uint8_t*)>(lib, "shm_abort");
  auto get = sym<int (*)(void*, const uint8_t*, long, uint64_t*,
                         uint64_t*)>(lib, "shm_get");
  auto release = sym<int (*)(void*, const uint8_t*)>(lib, "shm_release");
  auto del = sym<int (*)(void*, const uint8_t*)>(lib, "shm_delete");
  auto contains = sym<int (*)(void*, const uint8_t*)>(lib, "shm_contains");
  auto base_of = sym<void* (*)(void*)>(lib, "shm_store_base");
  auto stats = sym<int (*)(void*, uint64_t*, uint64_t*, uint64_t*,
                           uint64_t*)>(lib, "shm_stats");

  const std::string path = dir + "/store_test.shm";
  const uint64_t kCap = 1 << 20;  // 1 MiB
  CHECK(create(path.c_str(), kCap, 64) == 0);
  CHECK(create(path.c_str(), kCap, 64) < 0);  // O_EXCL: no clobber
  void* h = open_(path.c_str());
  CHECK(h != nullptr);
  uint8_t* base = static_cast<uint8_t*>(base_of(h));
  CHECK(base != nullptr);

  // create -> write -> seal -> get roundtrip.
  uint8_t id_a[kIdSize];
  make_id(id_a, 0xA1);
  uint64_t off = 0;
  CHECK(obj_create(h, id_a, 100, &off) == S_OK);
  CHECK(obj_create(h, id_a, 100, &off) == S_EXISTS);
  CHECK(contains(h, id_a) == 0);  // unsealed: not visible to get
  for (int i = 0; i < 100; i++) base[off + i] = static_cast<uint8_t>(i);
  CHECK(seal(h, id_a) == S_OK);
  CHECK(contains(h, id_a) == 1);
  uint64_t goff = 0, gsize = 0;
  CHECK(get(h, id_a, 0, &goff, &gsize) == S_OK);
  CHECK(goff == off && gsize == 100);
  for (int i = 0; i < 100; i++) CHECK(base[goff + i] == i);
  // Pinned (creator ref + get ref): delete must refuse.
  CHECK(del(h, id_a) == S_IN_USE);
  CHECK(release(h, id_a) == S_OK);
  CHECK(release(h, id_a) == S_OK);
  CHECK(del(h, id_a) == S_OK);
  CHECK(contains(h, id_a) == 0);

  // Missing ids: non-blocking miss vs timed-out blocking get.
  uint8_t id_b[kIdSize];
  make_id(id_b, 0xB2);
  CHECK(get(h, id_b, 0, &goff, &gsize) == S_NOT_FOUND);
  CHECK(get(h, id_b, 50, &goff, &gsize) == S_TIMEOUT);

  // Blocking get satisfied by a concurrent sealer.
  std::thread producer([&]() {
    usleep(50 * 1000);
    uint64_t o = 0;
    obj_create(h, id_b, 8, &o);
    std::memcpy(base + o, "blocked!", 8);
    seal(h, id_b);
  });
  CHECK(get(h, id_b, 5000, &goff, &gsize) == S_OK);
  producer.join();
  CHECK(gsize == 8 && std::memcmp(base + goff, "blocked!", 8) == 0);
  CHECK(release(h, id_b) == S_OK);  // get ref; creator ref still held

  // Abort an in-progress create.
  uint8_t id_c[kIdSize];
  make_id(id_c, 0xC3);
  CHECK(obj_create(h, id_c, 64, &off) == S_OK);
  CHECK(abort_(h, id_c) == S_OK);
  CHECK(contains(h, id_c) == 0);

  // LRU eviction: fill with released objects, then a create that only
  // fits if the store evicts. An oversized request still fails cleanly.
  for (int t = 0; t < 4; t++) {
    uint8_t id[kIdSize];
    make_id(id, static_cast<uint8_t>(0xD0 + t));
    CHECK(obj_create(h, id, 200 << 10, &off) == S_OK);
    CHECK(seal(h, id) == S_OK);
    CHECK(release(h, id) == S_OK);
  }
  uint8_t id_big[kIdSize];
  make_id(id_big, 0xEE);
  CHECK(obj_create(h, id_big, 600 << 10, &off) == S_OK);
  uint64_t used = 0, cap = 0, nobj = 0, nevict = 0;
  CHECK(stats(h, &used, &cap, &nobj, &nevict) == S_OK);
  CHECK(cap == kCap);
  CHECK(nevict >= 1);
  CHECK(used <= cap);
  uint8_t id_huge[kIdSize];
  make_id(id_huge, 0xFF);
  CHECK(obj_create(h, id_huge, 2 * kCap, &off) == S_FULL);

  close_(h);
  return 0;
}

// --------------------------------------------------------------- channel

static int test_channel(void* lib, const std::string& dir) {
  auto create = sym<int (*)(const char*, uint64_t, uint32_t, uint32_t)>(
      lib, "chan_create");
  auto open_ = sym<void* (*)(const char*)>(lib, "chan_open");
  auto close_handle = sym<void (*)(void*)>(lib, "chan_close_handle");
  auto write = sym<int (*)(void*, const uint8_t*, uint64_t, long)>(
      lib, "chan_write");
  auto read_acquire = sym<int (*)(void*, uint32_t, uint8_t**, uint64_t*,
                                  long)>(lib, "chan_read_acquire");
  auto read_release = sym<int (*)(void*, uint32_t)>(lib,
                                                    "chan_read_release");
  auto chan_close = sym<int (*)(void*)>(lib, "chan_close");

  const std::string path = dir + "/chan_test.shm";
  CHECK(create(path.c_str(), 256, 2, 4) == C_OK);
  void* h = open_(path.c_str());
  CHECK(h != nullptr);

  // Single value fans out to BOTH readers (broadcast semantics).
  CHECK(write(h, reinterpret_cast<const uint8_t*>("hello"), 5, 100)
        == C_OK);
  for (uint32_t r = 0; r < 2; r++) {
    uint8_t* ptr = nullptr;
    uint64_t len = 0;
    CHECK(read_acquire(h, r, &ptr, &len, 100) == C_OK);
    CHECK(len == 5 && std::memcmp(ptr, "hello", 5) == 0);
    CHECK(read_release(h, r) == C_OK);
  }
  // Reader id out of range.
  {
    uint8_t* ptr = nullptr;
    uint64_t len = 0;
    CHECK(read_acquire(h, 7, &ptr, &len, 0) == C_INVALID);
  }
  // Oversized payload.
  uint8_t big[512];
  CHECK(write(h, big, sizeof(big), 0) == C_TOO_LARGE);

  // Ring backpressure: with both readers at seq 1 and depth 4, writes
  // land up to seq 5; seq 6 must time out until a reader advances.
  uint8_t v = 0;
  for (int i = 0; i < 4; i++) CHECK(write(h, &v, 1, 100) == C_OK);
  CHECK(write(h, &v, 1, 50) == C_TIMEOUT);
  {
    uint8_t* ptr = nullptr;
    uint64_t len = 0;
    CHECK(read_acquire(h, 0, &ptr, &len, 100) == C_OK);
    CHECK(read_release(h, 0) == C_OK);
    CHECK(read_acquire(h, 1, &ptr, &len, 100) == C_OK);
    CHECK(read_release(h, 1) == C_OK);
  }
  CHECK(write(h, &v, 1, 100) == C_OK);  // slot reclaimed

  // Writer blocked on a full ring unblocks when a reader drains (the
  // compiled-DAG actor-loop handoff pattern).
  std::thread drainer([&]() {
    usleep(50 * 1000);
    uint8_t* ptr = nullptr;
    uint64_t len = 0;
    for (uint32_t r = 0; r < 2; r++) {
      while (read_acquire(h, r, &ptr, &len, 0) == C_OK)
        read_release(h, r);
    }
  });
  CHECK(write(h, &v, 1, 5000) == C_OK);
  drainer.join();

  // Close: pending writes fail, drained readers see ERR_CLOSED.
  CHECK(chan_close(h) == C_OK);
  CHECK(write(h, &v, 1, 100) == C_CLOSED);
  {
    uint8_t* ptr = nullptr;
    uint64_t len = 0;
    int rc = read_acquire(h, 0, &ptr, &len, 100);
    while (rc == C_OK) {
      read_release(h, 0);
      rc = read_acquire(h, 0, &ptr, &len, 100);
    }
    CHECK(rc == C_CLOSED);
  }
  close_handle(h);
  return 0;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <libstore.so> <libchannel.so> <workdir>\n",
                 argv[0]);
    return 2;
  }
  void* store_lib = dlopen(argv[1], RTLD_NOW | RTLD_LOCAL);
  if (!store_lib) {
    std::fprintf(stderr, "dlopen %s: %s\n", argv[1], dlerror());
    return 2;
  }
  void* chan_lib = dlopen(argv[2], RTLD_NOW | RTLD_LOCAL);
  if (!chan_lib) {
    std::fprintf(stderr, "dlopen %s: %s\n", argv[2], dlerror());
    return 2;
  }
  const std::string dir = argv[3];
  if (test_store(store_lib, dir) != 0) return 1;
  if (test_channel(chan_lib, dir) != 0) return 1;
  std::printf("NATIVE TESTS PASSED\n");
  return 0;
}
