// shm_store — shared-memory object store (plasma equivalent).
//
// TPU-native counterpart of the reference's plasma store
// (src/ray/object_manager/plasma/: dlmalloc over mmap, object lifecycle
// manager, LRU eviction, unix-socket client protocol). Key design change:
// instead of a store *daemon* serving create/get over a socket with fd
// passing, the entire store — header, object table, and data arena — lives
// in ONE file-backed mapping that every process on the node maps directly.
// Lookup/create/seal are lock-protected shared-memory operations (robust
// process-shared pthread mutex + condvar), so the hot path (get of a sealed
// object) is a table probe + refcount bump with zero syscalls and zero
// copies. This fits the TPU runtime's per-host layout: a handful of worker
// processes per host feeding chips, not thousands of clients.
//
// Concurrency: one global robust mutex (EOWNERDEAD-recovering) guards the
// table + allocator; a process-shared condvar broadcasts seals so blocked
// getters wake. Eviction is LRU over sealed refcount==0 objects, triggered
// on allocation failure (reference: eviction_policy.h).
//
// Build: g++ -O2 -fPIC -shared -o libray_tpu_store.so shm_store.cpp -lpthread

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <thread>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5241595f545055ULL;  // "RAY_TPU"
constexpr uint32_t kVersion = 1;
// MUST equal the Python ObjectID size (core/ids.py: 20-byte TaskID +
// 4-byte return index = 24): ids cross the ctypes boundary as
// exact-length buffers and find_slot memcmps the full kIdSize.
constexpr int kIdSize = 24;
constexpr uint64_t kAlign = 64;

enum SlotState : uint32_t {
  SLOT_FREE = 0,
  SLOT_CREATED = 1,   // allocated, being written by creator
  SLOT_SEALED = 2,    // immutable, readable
};

enum Status : int {
  OK = 0,
  ERR_EXISTS = -1,
  ERR_NOT_FOUND = -2,
  ERR_FULL = -3,
  ERR_TIMEOUT = -4,
  ERR_INVALID = -5,
  ERR_NOT_SEALED = -6,
  ERR_IN_USE = -7,
};

struct Slot {
  uint8_t id[kIdSize];
  uint32_t state;
  uint32_t _pad;
  uint64_t offset;      // into data arena (absolute file offset)
  uint64_t size;
  int64_t refcount;
  uint64_t lru_tick;    // bumped on each release-to-zero; lowest evicted first
};

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t n_slots;
  uint64_t capacity;       // bytes in data arena
  uint64_t data_start;     // file offset of arena
  uint64_t bytes_used;
  uint64_t tick;           // LRU clock
  uint64_t num_evictions;
  uint64_t num_created;
  pthread_mutex_t mutex;
  pthread_cond_t cond;
  // Slot table follows, then data arena.
};

struct Handle {
  int fd;
  uint8_t* base;
  uint64_t map_size;
  Header* hdr;
  Slot* slots;
  // Prefault worker (see shm_store_prefault): joined before munmap so
  // it can never madvise a torn-down (possibly reused) mapping.
  std::thread prefault_thread;
  std::atomic<bool> prefault_stop{false};
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

// Robust-mutex lock: recover if a holder died.
int lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}

inline void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

Slot* find_slot(Handle* st, const uint8_t* id) {
  // Linear probe over an open-addressed table keyed by the id's first 8
  // bytes (ids are uniformly random).
  uint64_t key;
  memcpy(&key, id, 8);
  uint32_t n = st->hdr->n_slots;
  uint32_t start = static_cast<uint32_t>(key % n);
  for (uint32_t i = 0; i < n; i++) {
    Slot* s = &st->slots[(start + i) % n];
    if (s->state != SLOT_FREE && memcmp(s->id, id, kIdSize) == 0) return s;
  }
  return nullptr;
}

Slot* find_empty_slot(Handle* st, const uint8_t* id) {
  uint64_t key;
  memcpy(&key, id, 8);
  uint32_t n = st->hdr->n_slots;
  uint32_t start = static_cast<uint32_t>(key % n);
  for (uint32_t i = 0; i < n; i++) {
    Slot* s = &st->slots[(start + i) % n];
    if (s->state == SLOT_FREE) return s;
  }
  return nullptr;
}

// First-fit allocation by scanning live slots (sorted scan each time).
// n_slots is small (<= 64Ki) and creates are not the hot path — gets are.
bool allocate(Handle* st, uint64_t size, uint64_t* out_offset) {
  Header* h = st->hdr;
  uint64_t need = align_up(size);
  if (need > h->capacity) return false;
  // Gather live extents.
  uint64_t cursor = h->data_start;
  const uint64_t arena_end = h->data_start + h->capacity;
  // Repeatedly find the live slot with the smallest offset >= cursor; if the
  // gap before it fits, take it. O(live^2) worst case; fine at this scale.
  while (true) {
    Slot* next = nullptr;
    for (uint32_t i = 0; i < h->n_slots; i++) {
      Slot* s = &st->slots[i];
      if (s->state == SLOT_FREE) continue;
      if (s->offset >= cursor && (!next || s->offset < next->offset)) next = s;
    }
    uint64_t gap_end = next ? next->offset : arena_end;
    if (gap_end - cursor >= need) {
      *out_offset = cursor;
      return true;
    }
    if (!next) return false;
    cursor = align_up(next->offset + next->size);
  }
}

// Evict LRU sealed refcount==0 objects until a `size` allocation fits.
bool evict_for(Handle* st, uint64_t size, uint64_t* out_offset) {
  Header* h = st->hdr;
  while (true) {
    if (allocate(st, size, out_offset)) return true;
    Slot* victim = nullptr;
    for (uint32_t i = 0; i < h->n_slots; i++) {
      Slot* s = &st->slots[i];
      if (s->state == SLOT_SEALED && s->refcount == 0 &&
          (!victim || s->lru_tick < victim->lru_tick)) {
        victim = s;
      }
    }
    if (!victim) return false;
    h->bytes_used -= victim->size;
    h->num_evictions++;
    victim->state = SLOT_FREE;
  }
}

void monotonic_deadline(struct timespec* ts, long timeout_ms) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create + initialize the store file. Returns 0 or -errno.
int shm_store_create(const char* path, uint64_t capacity, uint32_t n_slots) {
  uint64_t table_bytes = sizeof(Slot) * static_cast<uint64_t>(n_slots);
  uint64_t data_start = align_up(sizeof(Header) + table_bytes);
  uint64_t total = data_start + capacity;

  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return -errno;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    int e = errno;
    close(fd);
    unlink(path);
    return -e;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    int e = errno;
    close(fd);
    unlink(path);
    return -e;
  }
  Header* h = reinterpret_cast<Header*>(base);
  memset(h, 0, sizeof(Header) + table_bytes);
  h->version = kVersion;
  h->n_slots = n_slots;
  h->capacity = capacity;
  h->data_start = data_start;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&h->cond, &ca);
  pthread_condattr_destroy(&ca);

  h->magic = kMagic;  // last: marks initialized
  msync(base, sizeof(Header), MS_SYNC);
  munmap(base, total);
  close(fd);
  return 0;
}

void* shm_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat sb;
  if (fstat(fd, &sb) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, sb.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic || h->version != kVersion) {
    munmap(base, sb.st_size);
    close(fd);
    return nullptr;
  }
  Handle* st = new Handle;
  st->fd = fd;
  st->base = reinterpret_cast<uint8_t*>(base);
  st->map_size = sb.st_size;
  st->hdr = h;
  st->slots = reinterpret_cast<Slot*>(st->base + sizeof(Header));
  return st;
}

// Pre-fault the arena in the background so first-touch page faults
// (tmpfs page allocation + zeroing) don't sit on the first puts'
// critical path (reference: plasma pre-populates its dlmalloc arena).
// MADV_POPULATE_WRITE only populates page tables — safe to run
// concurrently with writers.
void shm_store_prefault(void* handle, uint64_t max_bytes) {
#ifdef MADV_POPULATE_WRITE
  Handle* st = reinterpret_cast<Handle*>(handle);
  if (!st) return;
  uint8_t* data = st->base;
  // The allocator hands out low offsets first, so pre-faulting a prefix
  // of the arena covers the hot working set without committing the
  // whole (possibly huge) store up front.
  uint64_t total = st->map_size;
  if (max_bytes && max_bytes < total) total = max_bytes;
  if (st->prefault_thread.joinable()) return;  // one per handle
  std::atomic<bool>* stop = &st->prefault_stop;
  st->prefault_thread = std::thread([data, total, stop]() {
    // Two phases: a fast head (the allocator's first objects land
    // there), then a gentle trickle for the rest so page
    // allocation+zeroing doesn't steal memory bandwidth from
    // foreground work right after cluster start. The stop flag is
    // honored between chunks; shm_store_close joins before munmap.
    const uint64_t chunk = 16ull << 20;
    const uint64_t fast_head = std::min<uint64_t>(total, 256ull << 20);
    for (uint64_t off = 0; off < fast_head; off += chunk) {
      if (stop->load()) return;
      (void)madvise(data + off, std::min(chunk, fast_head - off),
                    MADV_POPULATE_WRITE);
    }
    struct timespec ts = {0, 50 * 1000 * 1000};  // 50 ms between chunks
    for (uint64_t off = fast_head; off < total; off += chunk) {
      if (stop->load()) return;
      (void)madvise(data + off, std::min(chunk, total - off),
                    MADV_POPULATE_WRITE);
      nanosleep(&ts, nullptr);
    }
  });
#else
  (void)handle;
  (void)max_bytes;
#endif
}

// memcpy into a created (unsealed) object at absolute file offset `off`
// (as returned by shm_create) + `delta`. Called via ctypes, which drops
// the GIL for the copy — big puts neither hold the GIL nor block the
// caller's event loop.
void shm_store_write(void* handle, uint64_t off, uint64_t delta,
                     const uint8_t* src, uint64_t n) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  memcpy(st->base + off + delta, src, n);
}

void shm_store_close(void* handle) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  if (!st) return;
  st->prefault_stop.store(true);
  if (st->prefault_thread.joinable()) st->prefault_thread.join();
  munmap(st->base, st->map_size);
  close(st->fd);
  delete st;
}

// Allocate an object. On OK, *out_offset is the file offset to write into.
// Creator holds one reference (release after seal or abort).
int shm_create(void* handle, const uint8_t* id, uint64_t size,
               uint64_t* out_offset) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return ERR_INVALID;
  if (find_slot(st, id)) {
    unlock(h);
    return ERR_EXISTS;
  }
  Slot* slot = find_empty_slot(st, id);
  uint64_t offset = 0;
  if (!slot || !evict_for(st, size, &offset)) {
    unlock(h);
    return ERR_FULL;
  }
  memcpy(slot->id, id, kIdSize);
  slot->state = SLOT_CREATED;
  slot->offset = offset;
  slot->size = size;
  slot->refcount = 1;
  slot->lru_tick = ++h->tick;
  h->bytes_used += size;
  h->num_created++;
  *out_offset = offset;
  unlock(h);
  return OK;
}

int shm_seal(void* handle, const uint8_t* id) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return ERR_INVALID;
  Slot* s = find_slot(st, id);
  if (!s) {
    unlock(h);
    return ERR_NOT_FOUND;
  }
  s->state = SLOT_SEALED;
  pthread_cond_broadcast(&h->cond);
  unlock(h);
  return OK;
}

// Abort an in-progress create (creator crashed or errored before seal).
int shm_abort(void* handle, const uint8_t* id) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return ERR_INVALID;
  Slot* s = find_slot(st, id);
  if (!s) {
    unlock(h);
    return ERR_NOT_FOUND;
  }
  if (s->state != SLOT_CREATED) {
    unlock(h);
    return ERR_INVALID;
  }
  h->bytes_used -= s->size;
  s->state = SLOT_FREE;
  unlock(h);
  return OK;
}

// Blocking get: waits (timeout_ms; 0 = non-blocking, <0 = forever) for the
// object to be sealed, then pins it (refcount+1) and returns offset+size.
int shm_get(void* handle, const uint8_t* id, long timeout_ms,
            uint64_t* out_offset, uint64_t* out_size) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  struct timespec deadline;
  if (timeout_ms > 0) monotonic_deadline(&deadline, timeout_ms);
  if (lock(h) != 0) return ERR_INVALID;
  while (true) {
    Slot* s = find_slot(st, id);
    if (s && s->state == SLOT_SEALED) {
      s->refcount++;
      *out_offset = s->offset;
      *out_size = s->size;
      unlock(h);
      return OK;
    }
    if (timeout_ms == 0) {
      unlock(h);
      return ERR_NOT_FOUND;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&h->cond, &h->mutex);
    } else {
      rc = pthread_cond_timedwait(&h->cond, &h->mutex, &deadline);
    }
    if (rc == ETIMEDOUT) {
      unlock(h);
      return ERR_TIMEOUT;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mutex);
  }
}

int shm_release(void* handle, const uint8_t* id) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return ERR_INVALID;
  Slot* s = find_slot(st, id);
  if (!s) {
    unlock(h);
    return ERR_NOT_FOUND;
  }
  if (s->refcount > 0) s->refcount--;
  if (s->refcount == 0) s->lru_tick = ++h->tick;
  unlock(h);
  return OK;
}

// Delete a sealed, unreferenced object (owner-driven eviction: the
// distributed refcounter decided the object is out of scope).
int shm_delete(void* handle, const uint8_t* id) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return ERR_INVALID;
  Slot* s = find_slot(st, id);
  if (!s) {
    unlock(h);
    return ERR_NOT_FOUND;
  }
  if (s->refcount > 0) {
    unlock(h);
    return ERR_IN_USE;
  }
  h->bytes_used -= s->size;
  s->state = SLOT_FREE;
  unlock(h);
  return OK;
}

// Raw pointer into the mapped arena (offset from shm_create/shm_get).
// Valid while the object stays pinned — used by the native transfer
// plane to stream object bytes without copies through Python.
uint8_t* shm_data_pointer(void* handle, uint64_t offset) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  return st->base + offset;
}

// 1 if sealed-present, 0 otherwise.
int shm_contains(void* handle, const uint8_t* id) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return 0;
  Slot* s = find_slot(st, id);
  int present = (s && s->state == SLOT_SEALED) ? 1 : 0;
  unlock(h);
  return present;
}

// Base pointer of the mapped arena: offsets from shm_create/shm_get are
// relative to this (the C++ worker API writes/reads objects directly).
void* shm_store_base(void* handle) {
  return reinterpret_cast<Handle*>(handle)->base;
}

int shm_stats(void* handle, uint64_t* used, uint64_t* capacity,
              uint64_t* num_objects, uint64_t* num_evictions) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return ERR_INVALID;
  *used = h->bytes_used;
  *capacity = h->capacity;
  uint64_t n = 0;
  for (uint32_t i = 0; i < h->n_slots; i++) {
    if (st->slots[i].state != SLOT_FREE) n++;
  }
  *num_objects = n;
  *num_evictions = h->num_evictions;
  unlock(h);
  return OK;
}

// List up to max sealed object ids (for the object directory / spilling
// scans). Returns count written.
int shm_list(void* handle, uint8_t* out_ids, uint64_t* out_sizes,
             int64_t* out_refcounts, int max) {
  Handle* st = reinterpret_cast<Handle*>(handle);
  Header* h = st->hdr;
  if (lock(h) != 0) return 0;
  int n = 0;
  for (uint32_t i = 0; i < h->n_slots && n < max; i++) {
    Slot* s = &st->slots[i];
    if (s->state == SLOT_SEALED) {
      memcpy(out_ids + n * kIdSize, s->id, kIdSize);
      out_sizes[n] = s->size;
      out_refcounts[n] = s->refcount;
      n++;
    }
  }
  unlock(h);
  return n;
}

}  // extern "C"
