"""Operator resource management for the streaming executor.

Reference: python/ray/data/_internal/execution/resource_manager.py:25
(ResourceManager) and :246 (ReservationOpResourceAllocator): each
operator gets a RESERVED share of the global task/memory budget it can
always use, and the remainder is a SHARED pool handed out on demand.
Reservation guarantees liveness (no operator can be starved into
deadlock by another's runahead); the shared pool lets fast operators
use idle capacity. Also the per-operator stats the reference keeps in
python/ray/data/_internal/stats.py.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional


@dataclasses.dataclass
class OpStats:
    """Per-operator execution counters (reference: OpRuntimeMetrics)."""

    name: str
    tasks_submitted: int = 0
    tasks_finished: int = 0
    blocks_out: int = 0
    bytes_out: int = 0
    rows_out: int = 0
    wall_time_s: float = 0.0
    time_blocked_s: float = 0.0  # waiting on the resource budget
    peak_tasks_in_flight: int = 0
    peak_bytes_in_flight: int = 0
    actor_pool_size: int = 0      # actor-pool ops: peak pool size
    actor_pool_scaleups: int = 0

    def summary(self) -> str:
        return (f"{self.name}: tasks={self.tasks_finished}"
                f"/{self.tasks_submitted} blocks={self.blocks_out} "
                f"rows={self.rows_out} "
                f"bytes={self.bytes_out} wall={self.wall_time_s:.2f}s "
                f"blocked={self.time_blocked_s:.2f}s "
                f"peak_in_flight={self.peak_tasks_in_flight} tasks/"
                f"{self.peak_bytes_in_flight} bytes")


class _OpUsage:
    __slots__ = ("tasks", "bytes", "stats")

    def __init__(self, stats: OpStats):
        self.tasks = 0
        self.bytes = 0
        self.stats = stats


class ResourceManager:
    """Global budget split between operators via reservations.

    Budgets: total concurrently-running tasks and total in-flight bytes
    (completed-but-unconsumed outputs + a running-task estimate). Each
    registered op reserves ``reservation_ratio`` of an equal split; the
    rest is shared first-come-first-served. An op with nothing in flight
    may ALWAYS submit one task (liveness guarantee).
    """

    def __init__(self, max_tasks: int, max_bytes: int,
                 reservation_ratio: float = 0.5):
        self.max_tasks = max(1, max_tasks)
        self.max_bytes = max(1, max_bytes)
        self.reservation_ratio = reservation_ratio
        self._ops: Dict[str, _OpUsage] = {}
        self._reserved_tasks = 0
        self._reserved_bytes = 0

    # ---- registration ----
    def register_op(self, name: str) -> OpStats:
        base = name
        i = 1
        while name in self._ops:  # duplicate stage names
            i += 1
            name = f"{base}#{i}"
        stats = OpStats(name=name)
        self._ops[name] = _OpUsage(stats)
        n = len(self._ops)
        self._reserved_tasks = max(
            1, int(self.max_tasks * self.reservation_ratio / n))
        self._reserved_bytes = max(
            1, int(self.max_bytes * self.reservation_ratio / n))
        return stats

    # ---- accounting ----
    def _shared_in_use(self) -> tuple:
        st = sb = 0
        for u in self._ops.values():
            st += max(0, u.tasks - self._reserved_tasks)
            sb += max(0, u.bytes - self._reserved_bytes)
        return st, sb

    def can_submit(self, name: str, bytes_estimate: int = 0) -> bool:
        u = self._ops[name]
        if u.tasks == 0 and u.bytes == 0:
            return True  # liveness: an idle op always gets one task
        if u.tasks < self._reserved_tasks and \
                u.bytes + bytes_estimate <= self._reserved_bytes:
            return True
        shared_tasks = self.max_tasks - \
            self._reserved_tasks * len(self._ops)
        shared_bytes = self.max_bytes - \
            self._reserved_bytes * len(self._ops)
        st, sbytes = self._shared_in_use()
        return st < shared_tasks and sbytes + bytes_estimate <= shared_bytes

    def on_task_submitted(self, name: str, bytes_estimate: int) -> None:
        u = self._ops[name]
        u.tasks += 1
        u.bytes += bytes_estimate
        u.stats.tasks_submitted += 1
        u.stats.peak_tasks_in_flight = max(
            u.stats.peak_tasks_in_flight, u.tasks)
        u.stats.peak_bytes_in_flight = max(
            u.stats.peak_bytes_in_flight, u.bytes)

    def on_task_finished(self, name: str, bytes_estimate: int,
                         bytes_actual: Optional[int]) -> None:
        """Task done; its output stays charged (as bytes) until consumed."""
        u = self._ops[name]
        u.tasks -= 1
        u.stats.tasks_finished += 1
        if bytes_actual is not None and bytes_actual != bytes_estimate:
            u.bytes += bytes_actual - bytes_estimate

    def on_output_produced(self, name: str, bytes_held: int) -> None:
        """A streamed item landed (charged until consumed downstream)."""
        u = self._ops[name]
        u.bytes += bytes_held
        u.stats.peak_bytes_in_flight = max(
            u.stats.peak_bytes_in_flight, u.bytes)

    def on_output_consumed(self, name: str, bytes_held: int) -> None:
        u = self._ops[name]
        u.bytes = max(0, u.bytes - bytes_held)

    def all_stats(self) -> List[OpStats]:
        return [u.stats for u in self._ops.values()]

    def summary(self) -> str:
        lines = [s.summary() for s in self.all_stats()]
        return "\n".join(lines)


class ExecutionStats:
    """Stats of one streaming execution, kept for Dataset.stats()."""

    def __init__(self, op_stats: List[OpStats], wall_time_s: float):
        self.op_stats = op_stats
        self.wall_time_s = wall_time_s
        self.finished_at = time.time()

    def summary(self) -> str:
        lines = [f"Streaming execution: {self.wall_time_s:.2f}s total"]
        lines += ["  " + s.summary() for s in self.op_stats]
        return "\n".join(lines)
