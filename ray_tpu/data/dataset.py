"""Dataset — the lazy public API.

Reference: python/ray/data/dataset.py (`map_batches` :383, `iter_batches`
:3668, `materialize` :4615, `streaming_split` :1236) and read_api.py.
Execution is deferred until iteration/materialization and runs on the
streaming executor (executor.py).
"""

from __future__ import annotations

import builtins
import random as _random
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import datasource
from ray_tpu.data.block import (Block, batch_to_block, block_from_items,
                                block_from_pandas, block_to_numpy,
                                block_to_pandas, block_to_rows,
                                concat_blocks, format_batch,
                                iter_block_batches)
from ray_tpu.data.context import DataContext
from ray_tpu.data.executor import (AllToAllStage, MapStage, ShuffleStage,
                                   StreamingExecutor, _block_rows)


class Dataset:
    def __init__(self, read_tasks: List[Callable[[], Block]],
                 stages: Optional[List[Any]] = None):
        self._read_tasks = read_tasks
        self._stages = stages or []

    # ---------------- transformations (lazy) ----------------
    def _with(self, stage) -> "Dataset":
        return Dataset(self._read_tasks, self._stages + [stage])

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    compute: Optional[str] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    **_ignored) -> "Dataset":
        """Apply fn to batches. Class UDFs run on an actor pool."""
        if isinstance(fn, type):
            pool = concurrency or DataContext.get_current().actor_pool_size
            ctor_args = fn_constructor_args

            def make():
                return fn(*ctor_args)

            def apply(callable_obj, block: Block) -> Block:
                out = []
                for batch in iter_block_batches(block, batch_size,
                                                batch_format):
                    out.append(batch_to_block(callable_obj(batch)))
                return concat_blocks(out)

            return self._with(MapStage(
                f"MapBatches({fn.__name__})", apply,
                compute=("actors", pool, make)))

        def transform(block: Block, _fn=fn) -> Block:
            out = []
            for batch in iter_block_batches(block, batch_size, batch_format):
                out.append(batch_to_block(_fn(batch)))
            return concat_blocks(out)

        return self._with(MapStage(f"MapBatches({_name(fn)})", transform))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        def transform(block: Block) -> Block:
            return block_from_items([fn(r) for r in block_to_rows(block)])
        return self._with(MapStage(f"Map({_name(fn)})", transform,
                                   preserves_rows=True))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        def transform(block: Block) -> Block:
            rows: List[Dict] = []
            for r in block_to_rows(block):
                rows.extend(fn(r))
            return block_from_items(rows)
        return self._with(MapStage(f"FlatMap({_name(fn)})", transform))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        def transform(block: Block) -> Block:
            rows = [r for r in block_to_rows(block) if fn(r)]
            if not rows:
                return block.slice(0, 0)
            return block_from_items(rows)
        return self._with(MapStage(f"Filter({_name(fn)})", transform))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def transform(batch):
            batch[name] = fn(batch)
            return batch
        return self.map_batches(transform, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def transform(block: Block) -> Block:
            return block.drop_columns([c for c in cols
                                       if c in block.column_names])
        return self._with(MapStage(f"DropColumns({cols})", transform))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def transform(block: Block) -> Block:
            return block.select(cols)
        return self._with(MapStage(f"SelectColumns({cols})", transform))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def transform(block: Block) -> Block:
            return block.rename_columns(
                [mapping.get(c, c) for c in block.column_names])
        return self._with(MapStage("RenameColumns", transform))

    # ---------------- all-to-all ----------------
    # Built-in shuffles run as distributed two-phase exchanges
    # (map-partition → reduce-merge over ObjectRefs); see
    # executor.ShuffleStage. Reference:
    # python/ray/data/_internal/planner/exchange/.
    def repartition(self, num_blocks: int) -> "Dataset":
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        return self._with(ShuffleStage(f"Repartition({num_blocks})",
                                       "repartition",
                                       num_outputs=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(ShuffleStage("RandomShuffle", "shuffle",
                                       seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(ShuffleStage(f"Sort({key})", "sort", key=key,
                                       descending=descending))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ---------------- combining ----------------
    def union(self, *others: "Dataset") -> "Dataset":
        if self._stages:
            return self.materialize().union(*others)
        tasks = list(self._read_tasks)
        for o in others:
            tasks += o._read_tasks if not o._stages else \
                o.materialize()._read_tasks
        return Dataset(tasks)

    def zip(self, other: "Dataset") -> "Dataset":
        left = self.materialize()._read_tasks
        right = other.materialize()._read_tasks

        def exchange(blocks: List[Block]) -> List[Block]:
            import pyarrow as pa

            lt = concat_blocks([t() for t in left])
            rt = concat_blocks([t() for t in right])
            if lt.num_rows != rt.num_rows:
                raise ValueError("zip requires equal row counts")
            cols = {c: lt.column(c) for c in lt.column_names}
            for c in rt.column_names:
                name = c if c not in cols else f"{c}_1"
                cols[name] = rt.column(c)
            return [pa.table(cols)]

        return Dataset([lambda: concat_blocks([])],
                       [AllToAllStage("Zip", exchange)])

    def limit(self, n: int) -> "Dataset":
        from ray_tpu.data.executor import LimitStage

        return self._with(LimitStage(n))

    # ---------------- execution ----------------
    def iter_block_refs(self) -> Iterator[Any]:
        # Keep the executor so stats() reports THIS dataset's run.
        self._last_executor = StreamingExecutor()
        return self._last_executor.execute(self._read_tasks, self._stages)

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self.iter_block_refs():
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Any]:
        if local_shuffle_buffer_size:
            # Real shuffle buffer: accumulate >= buffer_size rows, shuffle,
            # drain down to buffer_size/2, refill (reference
            # _internal/block_batching shuffle-buffer semantics).
            rng = np.random.RandomState(local_shuffle_seed)
            buf: Optional[Block] = None
            bs = batch_size or 256
            low = max(local_shuffle_buffer_size // 2, bs)
            for block in self.iter_blocks():
                buf = block if buf is None else concat_blocks([buf, block])
                if buf.num_rows >= local_shuffle_buffer_size:
                    buf = buf.take(rng.permutation(buf.num_rows))
                    start = 0
                    while buf.num_rows - start >= low + bs:
                        yield format_batch(buf.slice(start, bs),
                                           batch_format)
                        start += bs
                    buf = buf.slice(start, buf.num_rows - start)
            if buf is not None and buf.num_rows:
                buf = buf.take(rng.permutation(buf.num_rows))
                start = 0
                while buf.num_rows - start >= bs:
                    yield format_batch(buf.slice(start, bs), batch_format)
                    start += bs
                if buf.num_rows - start and not drop_last:
                    yield format_batch(
                        buf.slice(start, buf.num_rows - start), batch_format)
            return
        carry: Optional[Block] = None
        for block in self.iter_blocks():
            if carry is not None and carry.num_rows:
                block = concat_blocks([carry, block])
                carry = None
            if batch_size is None:
                if block.num_rows:
                    yield format_batch(block, batch_format)
                continue
            start = 0
            while block.num_rows - start >= batch_size:
                yield format_batch(block.slice(start, batch_size),
                                   batch_format)
                start += batch_size
            if start < block.num_rows:
                carry = block.slice(start, block.num_rows - start)
        if carry is not None and carry.num_rows and not drop_last:
            yield format_batch(carry, batch_format)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from block_to_rows(block)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           **kwargs) -> Iterator[Any]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, **kwargs) -> Iterator[Any]:
        """TPU-native iterator: numpy batches device_put onto a sharding."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            if sharding is not None:
                yield {k: jax.device_put(v, sharding)
                       for k, v in batch.items()}
            else:
                yield {k: jax.numpy.asarray(v) for k, v in batch.items()}

    def streaming_split(self, n: int, *, equal: bool = False
                        ) -> List["DataIterator"]:
        """n iterators fed by ONE streaming execution inside a coordinator
        actor (reference dataset.py:1236 + _internal/execution/
        streaming_executor — the SplitCoordinator actor pattern). Blocks
        are produced on demand with per-split backpressure; each train
        worker consumes one split."""
        coordinator = _SplitCoordinator.options(max_concurrency=n + 1).remote(
            self._read_tasks, self._stages, n)
        return [DataIterator(coordinator=coordinator, split_index=i)
                for i in builtins.range(n)]

    def materialize(self) -> "Dataset":
        """Execute and pin the result as block REFS: values stay in the
        object plane; later consumers (worker-side read tasks) fetch
        them directly — the driver never touches block bytes."""
        refs = list(self.iter_block_refs())
        ds = Dataset([_ref_read_task(r) for r in refs])
        ds._pinned_refs = refs  # keep the driver-local refs alive
        return ds

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Execute and split into n datasets (reference: Dataset.split —
        used to hand shards to train workers). Row counting and slicing
        happen worker-side over refs; no block lands in the driver.

        equal=False (default): every row lands somewhere (first shards
        take the remainder). equal=True: all shards get exactly
        rows//n rows — the remainder rows are DROPPED (the reference's
        documented equalize behavior)."""
        refs = list(self.iter_block_refs())
        counts = ray_tpu.get([_block_rows.remote(r) for r in refs])
        rows = sum(counts)
        base = rows // n
        sizes = [base] * n
        if not equal:
            for i in builtins.range(rows - base * n):
                sizes[i] += 1
        shards = _plan_row_ranges(refs, counts, sizes)
        return [_shard_dataset(refs, shard) for shard in shards]

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> List["Dataset"]:
        """(train, test) split (reference: Dataset.train_test_split)."""
        if not 0 < test_size < 1:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        refs = list(ds.iter_block_refs())
        counts = ray_tpu.get([_block_rows.remote(r) for r in refs])
        rows = sum(counts)
        n_test = int(rows * test_size)
        shards = _plan_row_ranges(refs, counts, [rows - n_test, n_test])
        return [_shard_dataset(refs, shard) for shard in shards]

    # ---------------- writes ----------------
    def _write_blocks(self, path: str, ext: str, write_one) -> List[str]:
        """One output file per block (reference: write_parquet et al.,
        file-per-block layout)."""
        import os

        os.makedirs(path, exist_ok=True)
        written = []
        for i, block in enumerate(self.iter_blocks()):
            out = os.path.join(path, f"block_{i:05d}.{ext}")
            write_one(block, out)
            written.append(out)
        return written

    def _write_partitioned(self, path: str, ext: str, write_df,
                           partition_cols: List[str]) -> List[str]:
        """Hive layout: <path>/k1=v1/k2=v2/block_i_j.<ext> (reference:
        write_parquet's partition_cols / datasource/partitioning.py).
        Partition columns are dropped from the file payload — the path
        carries them, and the hive reader restores them."""
        import os

        written: List[str] = []
        for i, block in enumerate(self.iter_blocks()):
            df = block_to_pandas(block)
            missing = [c for c in partition_cols if c not in df.columns]
            if missing:
                raise ValueError(f"partition_cols not in block: {missing}")
            for j, (vals, group) in enumerate(
                    df.groupby(partition_cols, sort=True, dropna=False)):
                if not isinstance(vals, tuple):
                    vals = (vals,)
                sub = os.path.join(path, *(
                    f"{k}={v}" for k, v in zip(partition_cols, vals)))
                os.makedirs(sub, exist_ok=True)
                out = os.path.join(sub, f"block_{i:05d}_{j:03d}.{ext}")
                write_df(group.drop(columns=partition_cols), out)
                written.append(out)
        return written

    def write_parquet(self, path: str,
                      partition_cols: Optional[List[str]] = None
                      ) -> List[str]:
        if partition_cols:
            def one_df(df, out):
                import pyarrow as pa
                import pyarrow.parquet as pq

                pq.write_table(pa.Table.from_pandas(
                    df, preserve_index=False), out)

            return self._write_partitioned(path, "parquet", one_df,
                                           partition_cols)

        def one(block: Block, out: str):
            import pyarrow.parquet as pq

            pq.write_table(block, out)  # blocks ARE arrow tables

        return self._write_blocks(path, "parquet", one)

    def write_csv(self, path: str,
                  partition_cols: Optional[List[str]] = None
                  ) -> List[str]:
        if partition_cols:
            return self._write_partitioned(
                path, "csv", lambda df, out: df.to_csv(out, index=False),
                partition_cols)

        def one(block: Block, out: str):
            block_to_pandas(block).to_csv(out, index=False)

        return self._write_blocks(path, "csv", one)

    def write_webdataset(self, path: str) -> List[str]:
        """One tar shard per block; each row becomes the members
        ``<key>.<column>`` with type-directed encoding (str -> utf-8,
        int -> cls text, dict -> json, bytes raw, ndarray -> npy) —
        the inverse of read_webdataset (reference: write_webdataset)."""
        def one(block: Block, out: str):
            import io
            import json as jsonlib
            import tarfile

            from ray_tpu.data.block import block_to_rows

            # Tensor columns (FixedSizeList + tensor_shape metadata)
            # come out of block_to_rows as FLAT lists; restore their
            # ndarray form so they encode as .npy, not json.
            shapes: Dict[str, tuple] = {}
            for field in getattr(block, "schema", []) or []:
                meta = field.metadata or {}
                if b"tensor_shape" in meta:
                    shapes[field.name] = tuple(
                        jsonlib.loads(meta[b"tensor_shape"]))

            def encode(value) -> bytes:
                if isinstance(value, bytes):
                    return value
                if isinstance(value, str):
                    return value.encode("utf-8")
                if isinstance(value, (bool, int, np.integer)):
                    return str(int(value)).encode("utf-8")
                if isinstance(value, np.ndarray):
                    buf = io.BytesIO()
                    np.save(buf, value)
                    return buf.getvalue()
                return jsonlib.dumps(value, default=str).encode("utf-8")

            with tarfile.open(out, "w") as tar:
                for idx, row in enumerate(block_to_rows(block)):
                    key = str(row.get("__key__", f"{idx:08d}"))
                    for col, value in row.items():
                        if col == "__key__" or value is None:
                            continue
                        if col in shapes and isinstance(value, list):
                            value = np.asarray(value).reshape(
                                shapes[col])
                        data = encode(value)
                        info = tarfile.TarInfo(f"{key}.{col}")
                        info.size = len(data)
                        tar.addfile(info, io.BytesIO(data))

        return self._write_blocks(path, "tar", one)

    def write_json(self, path: str) -> List[str]:
        def one(block: Block, out: str):
            block_to_pandas(block).to_json(out, orient="records",
                                           lines=True)

        return self._write_blocks(path, "json", one)

    def write_numpy(self, path: str, column: str) -> List[str]:
        def one(block: Block, out: str):
            np.save(out, block_to_numpy(block)[column])

        return self._write_blocks(path, "npy", one)

    def to_tf(self, feature_columns: Union[str, List[str]],
              label_columns: Union[str, List[str]], *,
              batch_size: int = 1) -> "Any":
        """tf.data.Dataset of (features, labels) batches (reference:
        Dataset.to_tf). Signature is inferred from the first batch;
        single-column sides yield bare tensors, multi-column sides
        dicts. Gated on tensorflow."""
        import tensorflow as tf

        feats = [feature_columns] if isinstance(feature_columns, str) \
            else list(feature_columns)
        labels = [label_columns] if isinstance(label_columns, str) \
            else list(label_columns)

        def pick(batch, cols, single):
            if single:
                return batch[cols[0]]
            return {c: batch[c] for c in cols}

        single_f = isinstance(feature_columns, str)
        single_l = isinstance(label_columns, str)

        # Signature probe: one batch is computed (and discarded — every
        # tf epoch re-runs the pipeline via from_generator anyway); the
        # probe iterator is closed so the streaming executor unwinds
        # now instead of at GC.
        probe = iter(self.iter_batches(batch_size=batch_size))
        try:
            first = next(probe)
        except StopIteration:
            raise ValueError(
                "to_tf on an empty dataset: cannot infer the tf output "
                "signature from zero batches") from None
        finally:
            close = getattr(probe, "close", None)
            if close is not None:
                close()

        def spec(arr):
            a = np.asarray(arr)
            return tf.TensorSpec(shape=(None,) + a.shape[1:],
                                 dtype=tf.as_dtype(a.dtype))

        def side_spec(cols, single):
            if single:
                return spec(first[cols[0]])
            return {c: spec(first[c]) for c in cols}

        signature = (side_spec(feats, single_f),
                     side_spec(labels, single_l))

        def gen():
            for batch in self.iter_batches(batch_size=batch_size):
                yield (pick(batch, feats, single_f),
                       pick(batch, labels, single_l))

        return tf.data.Dataset.from_generator(
            gen, output_signature=signature)

    def write_tfrecords(self, path: str) -> List[str]:
        """tf.train.Example records, one file per block (reference:
        Dataset.write_tfrecords). Gated on tensorflow."""
        def one(block: Block, out: str):
            import tensorflow as tf

            from ray_tpu.data.block import block_to_rows

            with tf.io.TFRecordWriter(out) as w:
                for row in block_to_rows(block):
                    w.write(datasource.row_to_tf_example(
                        row).SerializeToString())

        return self._write_blocks(path, "tfrecords", one)

    # ---------------- consumption ----------------
    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: Dataset.random_sample).
        Seeded runs are deterministic without coordination: each
        block's rng derives from (seed, the block's stage ordinal), so
        every block — including blocks with identical content — draws
        an independent mask (content-derived seeds would correlate the
        sample across duplicate blocks)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")

        def transform(block: Block, idx: int) -> Block:
            rng = np.random.default_rng(
                None if seed is None else (seed, idx))
            keep = np.nonzero(
                rng.random(block.num_rows) < fraction)[0]
            return block.take(keep)

        return self._with(MapStage(f"RandomSample({fraction})",
                                   transform, wants_index=True))

    def take_batch(self, batch_size: int = 20,
                   *, batch_format: str = "numpy"):
        """First up-to-batch_size rows as ONE batch (reference:
        Dataset.take_batch)."""
        for batch in self.limit(batch_size).iter_batches(
                batch_size=batch_size, batch_format=batch_format):
            return batch
        raise ValueError("dataset is empty")

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_blocks())

    # Global aggregations (reference: Dataset.sum/min/max/mean/std/
    # unique over AggregateFns): per-block moments computed as remote
    # tasks, only tiny accumulators reach the driver.
    def _column_stats(self, col: str) -> Dict[str, Any]:
        # One fan-out computes every stat; memoized so min+max+mean+std
        # on the same dataset pay the remote pass once.
        cache = getattr(self, "_stats_cache", None)
        if cache is None:
            cache = self._stats_cache = {}
        if col in cache:
            return cache[col]
        parts = ray_tpu.get([_block_stats.remote(ref, col)
                             for ref in self.iter_block_refs()])
        acc = {"_n": 0, "_m": 0.0, "_m2": 0.0, "_mn": 0, "sum": None,
               "min": None, "max": None}
        unordered = False  # sticky: one incomparable pair poisons min/max
        for p in parts:
            if p["_n"] == 0:
                continue
            acc["_mn"] += p.get("_mn", 0)
            acc.update(_welford_merge(acc, p))
            if p["sum"] is not None:
                acc["sum"] = p["sum"] if acc["sum"] is None \
                    else acc["sum"] + p["sum"]
            if unordered:
                continue
            try:
                acc["min"] = p["min"] if acc["min"] is None \
                    else min(acc["min"], p["min"])
                acc["max"] = p["max"] if acc["max"] is None \
                    else max(acc["max"], p["max"])
            except TypeError:
                # Cross-block incomparable types (numeric vs object):
                # the column has no global order — min/max undefined,
                # and a later comparable block must NOT re-seed them.
                unordered = True
                acc["min"] = acc["max"] = None
        cache[col] = acc
        return acc

    def sum(self, col: str):
        acc = self._column_stats(col)
        # Mixed numeric/object blocks: a sum over just the numeric
        # subset would be silently wrong — report None like a fully
        # non-numeric column.
        return acc["sum"] if acc["_mn"] == acc["_n"] else None

    def min(self, col: str):
        return self._column_stats(col)["min"]

    def max(self, col: str):
        return self._column_stats(col)["max"]

    def mean(self, col: str):
        acc = self._column_stats(col)
        # sum None ⇔ non-numeric (or empty); _mn < _n ⇔ some blocks
        # were object-typed and contributed zero moments: both make the
        # merged mean meaningless.
        return acc["_m"] if acc["_n"] and acc["sum"] is not None \
            and acc["_mn"] == acc["_n"] else None

    def std(self, col: str, ddof: int = 1):
        import math

        acc = self._column_stats(col)
        if acc["_n"] <= ddof or acc["sum"] is None \
                or acc["_mn"] != acc["_n"]:
            return None
        return math.sqrt(acc["_m2"] / (acc["_n"] - ddof))

    def unique(self, col: str) -> List[Any]:
        """Distinct values of a column (reference: Dataset.unique) —
        per-block uniques as remote tasks, set-merged in the driver."""
        parts = ray_tpu.get([_block_unique.remote(ref, col)
                             for ref in self.iter_block_refs()])
        seen: Dict[Any, None] = {}
        for vals in parts:
            for v in vals:
                seen.setdefault(v, None)
        return list(seen)

    def schema(self):
        for b in self.iter_blocks():
            return b.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def to_pandas(self):
        return block_to_pandas(concat_blocks(list(self.iter_blocks())))

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return block_to_numpy(concat_blocks(list(self.iter_blocks())))

    def stats(self) -> str:
        """Plan summary + per-operator stats of THIS dataset's most
        recent streaming execution (reference: Dataset.stats() /
        _internal/stats.py)."""
        plan = f"Dataset(read_tasks={len(self._read_tasks)}, " \
               f"stages={[getattr(s, 'name', '?') for s in self._stages]})"
        ex = getattr(self, "_last_executor", None)
        if ex is not None and ex.last_stats is not None:
            return plan + "\n" + ex.last_stats.summary()
        return plan

    def __repr__(self) -> str:
        return self.stats()


@ray_tpu.remote
class _SplitCoordinator:
    """Owns ONE streaming execution, dispatches blocks to splits on demand.

    Blocks go into a single bounded queue and each get_next() pops the next
    available one (first-come-first-served — the reference's output-splitter
    dispatch, data/_internal/execution/operators/output_splitter.py). This
    cannot deadlock under any consumption order: a split that is consumed
    sequentially simply drains more blocks. The bounded queue gives
    backpressure: the producer stalls when all consumers fall behind,
    which stalls upstream task submission via the executor's bounded
    in-flight window."""

    def __init__(self, read_tasks, stages, n: int):
        import queue as _q
        import threading as _t

        from ray_tpu.data.executor import StreamingExecutor

        self._queue = _q.Queue(maxsize=max(2, 2 * n))
        self._n = n

        def produce():
            try:
                for ref in StreamingExecutor().execute(read_tasks, stages):
                    block = ray_tpu.get(ref)
                    self._queue.put(("block", block))
            except BaseException as e:  # surface to all consumers
                for _ in builtins.range(n):
                    self._queue.put(("error", repr(e)))
                return
            # one sentinel per split; each consumer stops at its first one
            for _ in builtins.range(n):
                self._queue.put(("done", None))

        self._producer = _t.Thread(target=produce, daemon=True)
        self._producer.start()

    def get_next(self, split_index: int):
        kind, payload = self._queue.get()
        if kind == "error":
            raise RuntimeError(f"streaming_split producer failed: {payload}")
        return payload  # Block or None when done


class DataIterator:
    """One split of a streaming_split — iterable on a remote worker.
    Holds either a coordinator actor handle (streaming) or a fixed list of
    block refs (materialized)."""

    def __init__(self, block_refs: Optional[List[Any]] = None,
                 coordinator=None, split_index: int = 0):
        self._refs = block_refs
        self._coordinator = coordinator
        self._split_index = split_index

    def _iter_local_blocks(self) -> Iterator[Block]:
        if self._coordinator is not None:
            while True:
                block = ray_tpu.get(
                    self._coordinator.get_next.remote(self._split_index))
                if block is None:
                    return
                yield block
        else:
            for ref in self._refs or []:
                yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        carry: Optional[Block] = None
        for block in self._iter_local_blocks():
            if carry is not None and carry.num_rows:
                block = concat_blocks([carry, block])
                carry = None
            if batch_size is None:
                if block.num_rows:
                    yield format_batch(block, batch_format)
                continue
            start = 0
            while block.num_rows - start >= batch_size:
                yield format_batch(block.slice(start, batch_size),
                                   batch_format)
                start += batch_size
            if start < block.num_rows:
                carry = block.slice(start, block.num_rows - start)
        if carry is not None and carry.num_rows and not drop_last:
            yield format_batch(carry, batch_format)

    def count(self) -> int:
        return sum(b.num_rows for b in self._iter_local_blocks())


def _welford_merge(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Merge two (n, mean, M2) moment sets (Chan et al.)."""
    n = a["_n"] + b["_n"]
    if n == 0:
        return {"_n": 0, "_m": 0.0, "_m2": 0.0}
    delta = b["_m"] - a["_m"]
    return {
        "_n": n,
        "_m": a["_m"] + delta * b["_n"] / n,
        "_m2": a["_m2"] + b["_m2"] + delta * delta * a["_n"] * b["_n"] / n,
    }


@ray_tpu.remote
def _block_stats(block: Block, col: str) -> Dict[str, Any]:
    """Per-block column moments for the global aggregations. Null rows
    are excluded from every statistic (pandas skipna semantics); _n is
    the NON-NULL count so the Welford merge stays consistent. Moments
    and sum are computed only for numeric dtypes (min/max are defined
    for any orderable column, e.g. strings); int sums keep their exact
    Python-int value (no float coercion)."""
    import pandas as pd

    def _py(v):
        return v.item() if hasattr(v, "item") else v

    s = block_to_pandas(block)[col].dropna()
    n = int(len(s))
    # _mn = rows that contributed MOMENTS (numeric blocks only). The
    # driver compares it against _n: when a column is numeric in some
    # blocks and object-typed in others, mean/std/sum over just the
    # numeric subset would be silently wrong, so they become None.
    out: Dict[str, Any] = {"_n": n, "_m": 0.0, "_m2": 0.0, "_mn": 0,
                           "sum": None, "min": None, "max": None}
    if n == 0:
        return out
    out["min"] = _py(s.min())
    out["max"] = _py(s.max())
    if pd.api.types.is_numeric_dtype(s):
        mean = float(s.mean())
        out["_m"] = mean
        out["_m2"] = float(((s - mean) ** 2).sum())
        out["sum"] = _py(s.sum())
        out["_mn"] = n
    return out


@ray_tpu.remote
def _block_unique(block: Block, col: str) -> List[Any]:
    import pandas as pd

    vals = pd.unique(block_to_pandas(block)[col])
    return [v.item() if hasattr(v, "item") else v for v in vals]


@ray_tpu.remote
def _partial_agg(block: Block, key: str, init, update) -> Dict[Any, Any]:
    """Per-block partial aggregation (map side of a groupby)."""
    df = block_to_pandas(block)
    out: Dict[Any, Any] = {}
    for k, group in df.groupby(key):
        acc = out.get(k, init())
        out[k] = update(acc, group)
    return out


class GroupedData:
    """Hash aggregation: per-block partial aggs computed as remote tasks,
    only the (small) per-key accumulators reach the driver for the final
    merge (reference: python/ray/data/grouped_data.py over the exchange
    task graph)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, col: Optional[str], init, update, merge, finalize=None):
        key = self._key
        partial_refs = [_partial_agg.remote(ref, key, init, update)
                        for ref in self._ds.iter_block_refs()]
        partials: Dict[Any, Any] = {}
        for part in ray_tpu.get(partial_refs):
            for k, acc in part.items():
                partials[k] = merge(partials[k], acc) \
                    if k in partials else acc
        rows = []
        for k in sorted(partials, key=lambda x: (x is None, x)):
            v = partials[k]
            if finalize:
                v = finalize(v)
            rows.append({key: k, **v})
        return Dataset(datasource.items_tasks(rows, parallelism=1))

    def count(self) -> Dataset:
        return self._agg(
            None, lambda: {"count()": 0},
            lambda acc, g: {"count()": acc["count()"] + len(g)},
            lambda a, b: {"count()": a["count()"] + b["count()"]})

    def sum(self, col: str) -> Dataset:
        name = f"sum({col})"
        return self._agg(
            col, lambda: {name: 0},
            lambda acc, g: {name: acc[name] + g[col].sum()},
            lambda a, b: {name: a[name] + b[name]})

    def min(self, col: str) -> Dataset:
        name = f"min({col})"
        return self._agg(
            col, lambda: {name: None},
            lambda acc, g: {name: g[col].min() if acc[name] is None
                            else min(acc[name], g[col].min())},
            lambda a, b: {name: min(a[name], b[name])})

    def max(self, col: str) -> Dataset:
        name = f"max({col})"
        return self._agg(
            col, lambda: {name: None},
            lambda acc, g: {name: g[col].max() if acc[name] is None
                            else max(acc[name], g[col].max())},
            lambda a, b: {name: max(a[name], b[name])})

    def mean(self, col: str) -> Dataset:
        name = f"mean({col})"
        return self._agg(
            col, lambda: {"_s": 0.0, "_n": 0},
            lambda acc, g: {"_s": acc["_s"] + g[col].sum(),
                            "_n": acc["_n"] + len(g)},
            lambda a, b: {"_s": a["_s"] + b["_s"], "_n": a["_n"] + b["_n"]},
            finalize=lambda acc: {name: acc["_s"] / max(acc["_n"], 1)})

    def std(self, col: str, ddof: int = 1) -> Dataset:
        """Sample std per group via mergeable Welford (n, mean, M2)
        moments — numerically stable for large-mean data (reference:
        data/aggregate.py Std uses the same merge). n <= ddof yields
        None (pandas/numpy return NaN there)."""
        import math

        name = f"std({col})"

        def upd(acc, g):
            # Chan et al. parallel update with the group's own moments.
            n_b = int(len(g))
            if n_b == 0:
                return acc
            mean_b = float(g[col].mean())
            m2_b = float(((g[col] - mean_b) ** 2).sum())
            return _welford_merge(acc, {"_n": n_b, "_m": mean_b,
                                        "_m2": m2_b})

        return self._agg(
            col, lambda: {"_n": 0, "_m": 0.0, "_m2": 0.0},
            upd, _welford_merge,
            finalize=lambda acc: {name: math.sqrt(
                acc["_m2"] / (acc["_n"] - ddof))
                if acc["_n"] > ddof else None})

    def map_groups(self, fn: Callable) -> Dataset:
        """Apply fn to each COMPLETE group (fn: pandas.DataFrame ->
        DataFrame | dict of columns). Groups are made partition-complete
        by a distributed hash exchange, then fn runs inside partition
        tasks — whole groups never land in the driver (reference:
        grouped_data.py map_groups over the exchange task graph)."""
        key = self._key
        ds = self._ds._with(ShuffleStage(
            f"HashGroups({key})", "hash", key=key))

        def apply(df):
            import pandas as pd

            outs = []
            for _, g in df.groupby(key, sort=True, dropna=False):
                r = fn(g)
                if not isinstance(r, pd.DataFrame):
                    r = pd.DataFrame(r)
                outs.append(r)
            return pd.concat(outs, ignore_index=True) if outs \
                else df.iloc[0:0]

        return ds.map_batches(apply, batch_format="pandas",
                              batch_size=None)


def _name(fn) -> str:
    return getattr(fn, "__name__", repr(fn))


def _ref_read_task(ref):
    """Read task resolving a pinned block ref (worker-side fetch)."""
    return lambda: ray_tpu.get(ref)


def _ref_slice_task(ref, start: int, length: int):
    return lambda: ray_tpu.get(ref).slice(start, length)


def _shard_dataset(refs, shard) -> "Dataset":
    """Dataset over (ref, start, len) pieces; empty shards keep the
    source schema via a zero-length slice of the first block."""
    tasks = [_ref_slice_task(r, s, ln) for r, s, ln in shard]
    if not tasks:
        tasks = [_ref_slice_task(refs[0], 0, 0)] if refs else \
            [lambda: block_from_items([])]
    ds = Dataset(tasks)
    ds._pinned_refs = refs
    return ds


def _plan_row_ranges(refs, counts: List[int],
                     sizes: List[int]) -> List[List[tuple]]:
    """Assign contiguous global row ranges of sizes[i] to each shard as
    (ref, start_in_block, length) pieces."""
    shards: List[List[tuple]] = [[] for _ in sizes]
    block_starts = []
    acc = 0
    for c in counts:
        block_starts.append(acc)
        acc += c
    shard_start = 0
    for i, size in enumerate(sizes):
        s, e = shard_start, shard_start + size
        for ref, bs, c in zip(refs, block_starts, counts):
            lo, hi = max(s, bs), min(e, bs + c)
            if lo < hi:
                shards[i].append((ref, lo - bs, hi - lo))
        shard_start = e
    return shards


# ---------------------------------------------------------------------------
# read_api (reference python/ray/data/read_api.py)
# ---------------------------------------------------------------------------

def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset(datasource.range_tasks(n, parallelism))


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset(datasource.items_tasks(items, parallelism))


def from_numpy(arrays, *, parallelism: int = 8) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    return Dataset(datasource.numpy_tasks(arrays, parallelism))


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    block = pa.Table.from_pandas(df, preserve_index=False)
    return Dataset([lambda: block])


def from_arrow(table) -> Dataset:
    return Dataset([lambda: table])


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 partitioning: Optional[str] = None) -> Dataset:
    """partitioning="hive": key=value path segments under the base dir
    become columns (reference: read_parquet's Partitioning("hive")
    default, datasource/partitioning.py)."""
    if partitioning == "hive":
        return Dataset(datasource.with_hive_partitions(
            lambda f: datasource.parquet_tasks([f], columns)[0], paths))
    return Dataset(datasource.parquet_tasks(paths, columns))


def read_parquet_bulk(paths, *, columns: Optional[List[str]] = None
                      ) -> Dataset:
    """Exactly one read task per GIVEN file path — no directory/glob
    expansion, no metadata prefetch (reference: read_api.py:944
    read_parquet_bulk, the many-small-files fast path)."""
    files = [paths] if isinstance(paths, str) else list(paths)
    if not files:
        raise ValueError("read_parquet_bulk requires file paths")

    def make(f):
        def read():
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)
        return read

    return Dataset([make(f) for f in files])


def read_csv(paths, *, partitioning: Optional[str] = None,
             **kwargs) -> Dataset:
    if partitioning == "hive":
        return Dataset(datasource.with_hive_partitions(
            lambda f: datasource.csv_tasks([f], **kwargs)[0], paths))
    return Dataset(datasource.csv_tasks(paths, **kwargs))


def read_json(paths, *, partitioning: Optional[str] = None) -> Dataset:
    if partitioning == "hive":
        return Dataset(datasource.with_hive_partitions(
            lambda f: datasource.json_tasks([f])[0], paths))
    return Dataset(datasource.json_tasks(paths))


def read_sql(sql: str, connection_factory, *, parallelism: int = 1,
             shard_column: Optional[str] = None) -> Dataset:
    """Query any DB-API connection into a Dataset (reference:
    read_api.py:2067 read_sql). Each read task calls
    ``connection_factory()`` inside the worker; with ``shard_column``
    (integer) and ``parallelism`` > 1 the query is MOD-sharded."""
    return Dataset(datasource.sql_tasks(sql, connection_factory,
                                        parallelism=parallelism,
                                        shard_column=shard_column))


def read_webdataset(paths, *, decode: bool = True) -> Dataset:
    """WebDataset tar shards -> one row per sample, columns named by
    member extension (reference: read_api.py:1860 read_webdataset).
    stdlib tarfile — needs no webdataset package."""
    return Dataset(datasource.webdataset_tasks(paths, decode=decode))


def read_avro(paths) -> Dataset:
    """Avro container files (reference: read_api.py:1492 read_avro).
    Gated on fastavro."""
    return Dataset(datasource.avro_tasks(paths))


def read_bigquery(project_id: str, *, dataset: Optional[str] = None,
                  query: Optional[str] = None) -> Dataset:
    """BigQuery table/query (reference: read_api.py:546 read_bigquery).
    Gated on google-cloud-bigquery."""
    return Dataset(datasource.bigquery_tasks(project_id,
                                             dataset=dataset,
                                             query=query))


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None) -> Dataset:
    """MongoDB collection/pipeline (reference: read_api.py:446
    read_mongo). Gated on pymongo."""
    return Dataset(datasource.mongo_tasks(uri, database, collection,
                                          pipeline=pipeline))


def read_text(paths) -> Dataset:
    return Dataset(datasource.text_tasks(paths))


def read_binary_files(paths) -> Dataset:
    return Dataset(datasource.binary_tasks(paths))


def read_numpy(paths, column: str = "data") -> Dataset:
    return Dataset(datasource.numpy_file_tasks(paths, column))


def read_images(paths, *, size=None, mode: str = None,
                include_paths: bool = False) -> Dataset:
    """Decode images into {'image': ndarray} rows (reference:
    read_api.py:792 read_images)."""
    return Dataset(datasource.image_tasks(paths, size=size, mode=mode,
                                          include_paths=include_paths))


def read_tfrecords(paths) -> Dataset:
    """Parse tf.train.Example TFRecord files into column rows
    (reference: read_api.py read_tfrecords). Gated on tensorflow."""
    return Dataset(datasource.tfrecord_tasks(paths))


def from_huggingface(hf_dataset, *, parallelism: int = 8) -> Dataset:
    """HuggingFace ``datasets.Dataset`` -> Dataset (reference:
    read_api.py from_huggingface). Zero-copy: hf datasets are
    arrow-backed; the underlying table is sliced into blocks."""
    # select/filter/shuffle leave an indices mapping over the ORIGINAL
    # backing table — reading .data raw would silently return
    # pre-filter rows. Materialize the view first.
    if getattr(hf_dataset, "_indices", None) is not None:
        hf_dataset = hf_dataset.flatten_indices()
    data = getattr(hf_dataset, "data", None)
    table = getattr(data, "table", data)
    if table is None or not hasattr(table, "num_rows"):
        raise TypeError(
            f"expected a datasets.Dataset (arrow-backed); got "
            f"{type(hf_dataset).__name__}")
    table = table.combine_chunks()
    n = table.num_rows
    k = max(1, min(parallelism, n or 1))
    step = (n + k - 1) // k if n else 1

    def make(off):
        return lambda: table.slice(off, step)

    return Dataset([make(off) for off in
                    builtins.range(0, max(n, 1), step)])


def from_torch(torch_dataset) -> Dataset:
    """Map-style torch Dataset -> row Dataset (reference: read_api.py
    from_torch — each item becomes a row; tensor items land under
    'item')."""
    items = []
    for i in builtins.range(len(torch_dataset)):
        item = torch_dataset[i]
        if not isinstance(item, dict):
            item = {"item": item}
        items.append({k: (v.numpy() if hasattr(v, "numpy") else v)
                      for k, v in item.items()})
    return from_items(items)


def from_dask(ddf) -> Dataset:
    """Dask DataFrame -> Dataset, one block per dask partition
    (reference: read_api.py:2311 from_dask). Partitions are computed
    THROUGH the cluster via the dask-on-ray scheduler
    (util/dask.py ray_dask_get), not dask's local threads. Gated on
    dask."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "from_dask requires the 'dask' package "
            "(pip install 'dask[dataframe]')") from e
    from ray_tpu.util.dask import ray_dask_get

    parts = ddf.to_delayed()
    if not parts:
        return from_items([])
    dfs = dask.compute(*parts, scheduler=ray_dask_get)
    tasks = [(lambda d=df: block_from_pandas(d)) for df in dfs]
    return Dataset(tasks)
