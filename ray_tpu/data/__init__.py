"""ray_tpu.data — streaming data engine (host-side, feeds TPU workers).

Parity map to the reference (python/ray/data/):
- Dataset lazy API        <- dataset.py:383 (map_batches), :3668
  (iter_batches), :4615 (materialize), :1236 (streaming_split)
- StreamingExecutor       <- _internal/execution/streaming_executor.py:48
- Blocks (Arrow)          <- block.py + _internal/arrow_block.py
- read_api                <- read_api.py:327,621
TPU-native addition: Dataset.iter_jax_batches(sharding=...) device-puts
batches straight onto a mesh sharding.
"""

from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import (DataIterator, Dataset, from_arrow,
                                  from_dask, from_huggingface,
                                  from_items, from_numpy, from_pandas,
                                  from_torch, range, read_avro,
                                  read_bigquery, read_binary_files,
                                  read_csv, read_images, read_json,
                                  read_mongo, read_numpy, read_parquet,
                                  read_parquet_bulk, read_sql,
                                  read_text, read_tfrecords,
                                  read_webdataset)
from ray_tpu.data import preprocessors

__all__ = [
    "DataContext",
    "DataIterator",
    "Dataset",
    "from_arrow",
    "from_dask",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "from_torch",
    "preprocessors",
    "range",
    "read_avro",
    "read_bigquery",
    "read_binary_files",
    "read_csv",
    "read_json",
    "read_images",
    "read_mongo",
    "read_numpy",
    "read_parquet",
    "read_parquet_bulk",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
