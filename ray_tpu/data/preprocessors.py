"""Preprocessors: fit on a Dataset, transform Datasets/batches.

Reference: python/ray/data/preprocessors/ (Preprocessor base with
fit/transform/transform_batch; StandardScaler, MinMaxScaler,
LabelEncoder, OneHotEncoder, Concatenator, Chain). Stats are computed
with the Dataset aggregation API; transforms are map_batches stages.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self.transform_batch, batch_format="pandas")

    def transform_batch(self, batch):
        raise NotImplementedError

    def _fit(self, ds) -> None:
        pass

    def _needs_fit(self) -> bool:
        return True


def _col_stats(ds, columns: List[str]) -> Dict[str, Dict[str, float]]:
    """One pass: count/sum/sumsq/min/max per column."""
    stats = {c: {"count": 0, "sum": 0.0, "sumsq": 0.0,
                 "min": float("inf"), "max": float("-inf")}
             for c in columns}
    for block in ds.iter_blocks():
        from ray_tpu.data.block import block_to_numpy

        arrays = block_to_numpy(block)
        for c in columns:
            v = np.asarray(arrays[c], dtype=np.float64)
            s = stats[c]
            s["count"] += v.size
            s["sum"] += float(v.sum())
            s["sumsq"] += float((v * v).sum())
            if v.size:
                s["min"] = min(s["min"], float(v.min()))
                s["max"] = max(s["max"], float(v.max()))
    return stats


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        raw = _col_stats(ds, self.columns)
        for c, s in raw.items():
            mean = s["sum"] / max(1, s["count"])
            var = s["sumsq"] / max(1, s["count"]) - mean * mean
            self.stats_[c] = (mean, max(var, 0.0) ** 0.5)

    def transform_batch(self, batch):
        for c in self.columns:
            mean, std = self.stats_[c]
            batch[c] = (batch[c] - mean) / (std if std > 0 else 1.0)
        return batch


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        raw = _col_stats(ds, self.columns)
        for c, s in raw.items():
            self.stats_[c] = (s["min"], s["max"])

    def transform_batch(self, batch):
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = hi - lo
            batch[c] = (batch[c] - lo) / (span if span > 0 else 1.0)
        return batch


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Dict = {}

    def _fit(self, ds) -> None:
        values = set()
        for block in ds.iter_blocks():
            from ray_tpu.data.block import block_to_pandas

            values.update(block_to_pandas(block)[self.label_column]
                          .unique().tolist())
        self.classes_ = {v: i for i, v in enumerate(sorted(values))}

    def transform_batch(self, batch):
        col = batch[self.label_column]
        unseen = set(col.unique()) - set(self.classes_)
        if unseen:
            raise ValueError(
                f"labels not seen at fit time: {sorted(unseen)!r}")
        batch[self.label_column] = col.map(self.classes_)
        return batch


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.categories_: Dict[str, List] = {}

    def _fit(self, ds) -> None:
        values: Dict[str, set] = {c: set() for c in self.columns}
        for block in ds.iter_blocks():
            from ray_tpu.data.block import block_to_pandas

            df = block_to_pandas(block)
            for c in self.columns:
                values[c].update(df[c].unique().tolist())
        self.categories_ = {c: sorted(v) for c, v in values.items()}

    def transform_batch(self, batch):
        for c in self.columns:
            for cat in self.categories_[c]:
                batch[f"{c}_{cat}"] = (batch[c] == cat).astype(np.int8)
            batch = batch.drop(columns=[c])
        return batch


class Concatenator(Preprocessor):
    """Concatenate feature columns into one vector column (the shape
    Train ingest wants)."""

    def __init__(self, columns: Optional[List[str]] = None,
                 output_column_name: str = "concat_out",
                 exclude: Optional[List[str]] = None):
        self.columns = columns
        self.output_column_name = output_column_name
        self.exclude = set(exclude or [])

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch):
        cols = self.columns or [c for c in batch.columns
                                if c not in self.exclude]
        mat = np.stack([np.asarray(batch[c], dtype=np.float64)
                        for c in cols], axis=1)
        out = batch.drop(columns=cols)
        out[self.output_column_name] = list(mat)
        return out


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, ds) -> "Chain":
        for p in self.preprocessors:
            ds = p.fit_transform(ds).materialize()
        self._fitted = True
        return self

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
