"""Datasources — lazy read tasks.

Reference: python/ray/data/datasource/ + read_api.py:327,621. A read is a
list of zero-arg callables, each producing one Block; the executor runs
them as remote tasks (streaming) like any other operator stage.
"""

from __future__ import annotations

import glob as globlib
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import (Block, block_from_items, block_from_numpy,
                                block_from_pandas)

ReadTask = Callable[[], Block]


def _chunk(n: int, k: int) -> List[range]:
    k = max(1, min(k, n)) if n else 1
    step = (n + k - 1) // k if n else 1
    return [range(i, min(i + step, n)) for i in range(0, n, step)]


def range_tasks(n: int, parallelism: int = 8) -> List[ReadTask]:
    def make(r: range) -> ReadTask:
        return lambda: block_from_numpy({"id": np.arange(r.start, r.stop)})
    return [make(r) for r in _chunk(n, parallelism)]


def items_tasks(items: List[Any], parallelism: int = 8) -> List[ReadTask]:
    chunks = _chunk(len(items), parallelism)

    def make(r: range) -> ReadTask:
        part = items[r.start:r.stop]
        if part and isinstance(part[0], dict) and any(
                isinstance(v, np.ndarray) for v in part[0].values()):
            # ndarray values ride the tensor-column path (reference:
            # from_items accepts array-valued rows).
            return lambda: _mixed_rows_to_block(part)
        return lambda: block_from_items(part)
    return [make(r) for r in chunks]


def numpy_tasks(arrays: Dict[str, np.ndarray],
                parallelism: int = 8) -> List[ReadTask]:
    n = len(next(iter(arrays.values()))) if arrays else 0

    def make(r: range) -> ReadTask:
        part = {k: v[r.start:r.stop] for k, v in arrays.items()}
        return lambda: block_from_numpy(part)
    return [make(r) for r in _chunk(n, parallelism)]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def parquet_tasks(paths, columns: Optional[List[str]] = None
                  ) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)
        return read
    return [make(f) for f in files]


def csv_tasks(paths, **read_options) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)
        return read
    return [make(f) for f in files]


def json_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import pyarrow.json as pajson

            return pajson.read_json(f)
        return read
    return [make(f) for f in files]


def text_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            with open(f) as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            return block_from_items([{"text": ln} for ln in lines])
        return read
    return [make(f) for f in files]


def binary_tasks(paths) -> List[ReadTask]:
    """One row per file: {'path', 'bytes'} (reference:
    read_binary_files)."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            with open(f, "rb") as fh:
                data = fh.read()
            return block_from_items([{"path": f, "bytes": data}])
        return read
    return [make(f) for f in files]


IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tiff",
                    ".webp")


def image_tasks(paths, size=None, mode: str = None,
                include_paths: bool = False) -> List[ReadTask]:
    """Decode image files into {'image': HxWxC uint8 array} rows
    (reference: read_api.py:792 read_images — PIL decode, optional
    resize/mode conversion, optional path column). Directories expand to
    their image files."""
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith(IMAGE_EXTENSIONS)]
    if not files:
        raise ValueError(f"no image files found under {paths!r}")

    def make(f: str) -> ReadTask:
        def read() -> Block:
            from PIL import Image

            with Image.open(f) as img:
                if mode:
                    img = img.convert(mode)
                if size:
                    # API takes (height, width) like the reference's
                    # read_images; PIL resize wants (width, height).
                    img = img.resize((size[1], size[0]))
                arr = np.asarray(img)
            # Tensor column (fixed-size list + shape metadata): HxWxC
            # arrays round-trip through block_to_numpy exactly.
            cols: Dict[str, Any] = {"image": arr[None]}
            if include_paths:
                cols["path"] = np.array([f])
            return block_from_numpy(cols)
        return read
    return [make(f) for f in files]


def numpy_file_tasks(paths, column: str = "data") -> List[ReadTask]:
    """One block per .npy file (reference: read_numpy)."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            arr = np.load(f)
            return block_from_numpy({column: arr})
        return read
    return [make(f) for f in files]


def tfrecord_tasks(paths) -> List[ReadTask]:
    """Parse TFRecord files of tf.train.Example into arrow blocks
    (reference: read_api.py read_tfrecords /
    _internal/datasource/tfrecords_datasource.py). Feature decoding
    follows the reference: bytes_list/float_list/int64_list; a feature
    with exactly one value becomes a scalar column, several values a
    list column. Gated on tensorflow (the wire format's Example proto
    lives there)."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            try:
                import tensorflow as tf
            except ImportError as e:
                raise ImportError(
                    "read_tfrecords requires tensorflow for the "
                    "tf.train.Example wire format") from e
            import pyarrow as pa

            columns: Dict[str, list] = {}
            rows = 0
            for raw in tf.data.TFRecordDataset([f]):
                ex = tf.train.Example()
                ex.ParseFromString(bytes(raw.numpy()))
                rows += 1
                for name, feat in ex.features.feature.items():
                    kind = feat.WhichOneof("kind")
                    if kind == "bytes_list":
                        vals = list(feat.bytes_list.value)
                    elif kind == "float_list":
                        vals = list(feat.float_list.value)
                    elif kind == "int64_list":
                        vals = list(feat.int64_list.value)
                    else:
                        vals = []
                    col = columns.setdefault(name, [None] * (rows - 1))
                    # Empty feature = null (the wire format cannot
                    # distinguish an empty list from a missing value;
                    # write_tfrecords emits empty features for None) —
                    # keeping [] here would force the whole column to
                    # list type and break scalar unwrapping.
                    col.append(vals if vals else None)
                for name, col in columns.items():
                    if len(col) < rows:
                        col.append(None)  # feature absent in this record
            # Column shape is decided PER COLUMN over the whole FILE:
            # unwrapping only single-value rows would mix scalars and
            # lists in one column (ArrowInvalid) when lengths vary.
            # (The Example wire format drops the scalar/list
            # distinction, so a file whose every value has length 1
            # necessarily reads back as scalars — same ambiguity as the
            # reference's tfrecords datasource.)
            out = {}
            for name, col in columns.items():
                if all(v is None or len(v) == 1 for v in col):
                    out[name] = [None if v is None else v[0] for v in col]
                else:
                    out[name] = col
            return pa.table(out)
        return read
    return [make(f) for f in files]


def sql_tasks(sql: str, connection_factory: Callable[[], Any],
              parallelism: int = 1,
              shard_column: Optional[str] = None) -> List[ReadTask]:
    """DB-API read tasks (reference: read_api.py:2067 read_sql — a
    query + a zero-arg connection factory; each task opens its own
    connection inside the worker).

    Default is ONE task running the query as-is (the reference's serial
    mode: most engines cannot split an arbitrary query). With
    ``shard_column`` (integer-typed) and ``parallelism`` > 1, task i
    wraps the query as ``SELECT * FROM (<sql>) WHERE shard_column %% N
    = i`` — the reference's MOD-sharding strategy — so shards scan
    disjoint row sets in parallel."""
    if parallelism > 1 and not shard_column:
        raise ValueError(
            "read_sql parallelism > 1 requires shard_column (an "
            "integer column to MOD-shard the query on); arbitrary SQL "
            "cannot be split safely")

    def run_query(query: str, params: tuple = ()) -> Block:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(query, params)
            names = [d[0] for d in cur.description or []]
            rows = cur.fetchall()
        finally:
            try:
                conn.close()
            except Exception:
                pass
        return block_from_items(
            [dict(zip(names, row)) for row in rows])

    if parallelism <= 1:
        return [lambda: run_query(sql)]

    def make(i: int) -> ReadTask:
        sharded = (f"SELECT * FROM ({sql}) "  # noqa: S608 — user SQL
                   f"WHERE ({shard_column} % {parallelism}) = {i}")
        return lambda: run_query(sharded)

    return [make(i) for i in range(parallelism)]


# WebDataset member decoding by extension (reference:
# read_api.py:1860 read_webdataset / _internal/datasource/
# webdataset_datasource.py default_decoder): keys group the files of
# one sample; well-known extensions decode, the rest stay bytes.
def _decode_wds_member(ext: str, data: bytes):
    import json as jsonlib

    if ext in ("txt", "text"):
        return data.decode("utf-8")
    if ext == "json":
        return jsonlib.loads(data.decode("utf-8"))
    if ext in ("cls", "cls2", "index"):
        return int(data.decode("utf-8").strip())
    if ext in ("npy",):
        import io

        return np.load(io.BytesIO(data))
    if "." + ext in IMAGE_EXTENSIONS:
        try:
            import io

            from PIL import Image

            with Image.open(io.BytesIO(data)) as img:
                return np.asarray(img)
        except Exception:
            return data
    return data


def _mixed_rows_to_block(rows: List[Dict[str, Any]]) -> Block:
    """Rows whose values may include ndarrays (decoded .npy / image
    members): uniform-shape ndarray columns go through the tensor-column
    path (block_from_numpy fixed-size lists), everything else through
    the plain items path; ragged keys null-fill."""
    import pyarrow as pa

    if not rows:
        return block_from_items(rows)
    keys: Dict[str, None] = {}
    for r in rows:
        for k in r:
            keys.setdefault(k, None)
    cols = {k: [r.get(k) for r in rows] for k in keys}
    tensors: Dict[str, np.ndarray] = {}
    for k, vals in list(cols.items()):
        if (all(isinstance(v, np.ndarray) for v in vals)
                and len({v.shape for v in vals}) == 1
                and vals[0].ndim >= 1):
            tensors[k] = np.stack(vals)
            del cols[k]
    table = pa.table(cols) if cols else None
    if tensors:
        t2 = block_from_numpy(tensors)
        if table is None:
            return t2
        for name in t2.column_names:
            table = table.append_column(t2.schema.field(name),
                                        t2.column(name))
    return table


def webdataset_tasks(paths, decode: bool = True) -> List[ReadTask]:
    """WebDataset tar shards -> one row per sample (reference:
    read_api.py:1860 read_webdataset). A sample is every tar member
    sharing a dotted basename prefix; the row is
    {"__key__": prefix, <ext>: decoded value, ...}. Pure stdlib
    (tarfile) — no webdataset package needed."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import tarfile

            samples: Dict[str, Dict[str, Any]] = {}
            order: List[str] = []
            with tarfile.open(f) as tar:
                for m in tar:
                    if not m.isfile():
                        continue
                    base = os.path.basename(m.name)
                    if "." in base:
                        key, ext = base.split(".", 1)
                    else:
                        key, ext = base, ""
                    data = tar.extractfile(m).read()
                    row = samples.get(key)
                    if row is None:
                        row = samples[key] = {"__key__": key}
                        order.append(key)
                    row[ext] = (_decode_wds_member(ext.lower(), data)
                                if decode else data)
            return _mixed_rows_to_block([samples[k] for k in order])
        return read

    return [make(f) for f in files]


def avro_tasks(paths) -> List[ReadTask]:
    """Avro object-container files (reference: read_api.py:1492
    read_avro). Gated on fastavro — the container codec set (deflate,
    snappy) is not worth vendoring."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            try:
                import fastavro
            except ImportError as e:
                raise ImportError(
                    "read_avro requires the 'fastavro' package "
                    "(pip install fastavro)") from e
            with open(f, "rb") as fh:
                rows = list(fastavro.reader(fh))
            return block_from_items(rows)
        return read

    return [make(f) for f in files]


def bigquery_tasks(project_id: str, dataset: Optional[str] = None,
                   query: Optional[str] = None) -> List[ReadTask]:
    """BigQuery read (reference: read_api.py:546 read_bigquery). Gated
    on google-cloud-bigquery; one task runs the query (or a full-table
    scan of ``dataset``) and pages rows into a block."""
    if bool(dataset) == bool(query):
        raise ValueError("pass exactly one of dataset= or query=")

    def read() -> Block:
        try:
            from google.cloud import bigquery  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_bigquery requires the 'google-cloud-bigquery' "
                "package") from e
        client = bigquery.Client(project=project_id)
        q = query or f"SELECT * FROM `{dataset}`"  # noqa: S608
        rows = [dict(r) for r in client.query(q).result()]
        return block_from_items(rows)

    return [read]


def mongo_tasks(uri: str, database: str, collection: str,
                pipeline: Optional[List[dict]] = None) -> List[ReadTask]:
    """MongoDB read (reference: read_api.py:446 read_mongo). Gated on
    pymongo; one task per call runs the aggregation pipeline (or a full
    find) inside the worker."""

    def read() -> Block:
        try:
            import pymongo
        except ImportError as e:
            raise ImportError(
                "read_mongo requires the 'pymongo' package") from e
        client = pymongo.MongoClient(uri)
        try:
            coll = client[database][collection]
            cursor = (coll.aggregate(pipeline) if pipeline
                      else coll.find())
            rows = []
            for doc in cursor:
                doc.pop("_id", None)
                rows.append(dict(doc))
        finally:
            client.close()
        return block_from_items(rows)

    return [read]


# ---------------------------------------------------------- partitioning
def parse_hive_partitions(file_path: str, base_path: str
                          ) -> Dict[str, str]:
    """key=value path segments between base_path and the file
    (reference: datasource/partitioning.py Partitioning("hive"))."""
    rel = os.path.relpath(os.path.dirname(os.path.abspath(file_path)),
                          os.path.abspath(base_path))
    out: Dict[str, str] = {}
    for seg in rel.split(os.sep):
        if "=" in seg:
            k, v = seg.split("=", 1)
            out[k] = v
    return out


def _expand_paths_recursive(paths) -> List[str]:
    """Like _expand_paths but walks directories recursively — needed
    for hive layouts (<base>/k=v/file)."""
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in sorted(os.walk(p)):
                out.extend(sorted(
                    os.path.join(root, n) for n in names
                    if not n.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def with_hive_partitions(tasks_for_file: Callable[[str], ReadTask],
                         paths) -> List[ReadTask]:
    """Wrap a per-file reader so each block gains the hive key=value
    columns parsed from its path (constant within the file)."""
    import pandas as pd

    from ray_tpu.data.block import block_to_pandas as _to_pd

    base = paths if isinstance(paths, str) else paths[0]
    files = _expand_paths_recursive(paths)

    def make(f: str) -> ReadTask:
        inner = tasks_for_file(f)
        parts = parse_hive_partitions(f, base)

        def read() -> Block:
            block = inner()
            if not parts:
                return block
            df = _to_pd(block)
            for k, v in parts.items():
                # Numeric-looking partition values load as numbers
                # (write side stringifies them; int survives round-trip).
                try:
                    df[k] = int(v)
                except ValueError:
                    try:
                        df[k] = float(v)
                    except ValueError:
                        df[k] = v
            return block_from_pandas(pd.DataFrame(df))
        return read

    return [make(f) for f in files]


def row_to_tf_example(row: Dict[str, Any]):
    """One dataset row -> tf.train.Example (write_tfrecords helper)."""
    import tensorflow as tf

    feats = {}
    for name, value in row.items():
        if isinstance(value, (list, tuple, np.ndarray)):
            vals = [v for v in value if v is not None]
        elif value is None:
            vals = []  # nullable column -> empty feature
        else:
            vals = [value]
        if not vals:
            feats[name] = tf.train.Feature()
        elif isinstance(vals[0], bytes):
            feats[name] = tf.train.Feature(
                bytes_list=tf.train.BytesList(value=vals))
        elif isinstance(vals[0], str):
            feats[name] = tf.train.Feature(
                bytes_list=tf.train.BytesList(
                    value=[v.encode() for v in vals]))
        elif isinstance(vals[0], (int, np.integer, bool, np.bool_)):
            feats[name] = tf.train.Feature(
                int64_list=tf.train.Int64List(
                    value=[int(v) for v in vals]))
        else:
            feats[name] = tf.train.Feature(
                float_list=tf.train.FloatList(
                    value=[float(v) for v in vals]))
    return tf.train.Example(
        features=tf.train.Features(feature=feats))
