"""Datasources — lazy read tasks.

Reference: python/ray/data/datasource/ + read_api.py:327,621. A read is a
list of zero-arg callables, each producing one Block; the executor runs
them as remote tasks (streaming) like any other operator stage.
"""

from __future__ import annotations

import glob as globlib
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import (Block, block_from_items, block_from_numpy,
                                block_from_pandas)

ReadTask = Callable[[], Block]


def _chunk(n: int, k: int) -> List[range]:
    k = max(1, min(k, n)) if n else 1
    step = (n + k - 1) // k if n else 1
    return [range(i, min(i + step, n)) for i in range(0, n, step)]


def range_tasks(n: int, parallelism: int = 8) -> List[ReadTask]:
    def make(r: range) -> ReadTask:
        return lambda: block_from_numpy({"id": np.arange(r.start, r.stop)})
    return [make(r) for r in _chunk(n, parallelism)]


def items_tasks(items: List[Any], parallelism: int = 8) -> List[ReadTask]:
    chunks = _chunk(len(items), parallelism)

    def make(r: range) -> ReadTask:
        part = items[r.start:r.stop]
        return lambda: block_from_items(part)
    return [make(r) for r in chunks]


def numpy_tasks(arrays: Dict[str, np.ndarray],
                parallelism: int = 8) -> List[ReadTask]:
    n = len(next(iter(arrays.values()))) if arrays else 0

    def make(r: range) -> ReadTask:
        part = {k: v[r.start:r.stop] for k, v in arrays.items()}
        return lambda: block_from_numpy(part)
    return [make(r) for r in _chunk(n, parallelism)]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def parquet_tasks(paths, columns: Optional[List[str]] = None
                  ) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)
        return read
    return [make(f) for f in files]


def csv_tasks(paths, **read_options) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)
        return read
    return [make(f) for f in files]


def json_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            import pyarrow.json as pajson

            return pajson.read_json(f)
        return read
    return [make(f) for f in files]


def text_tasks(paths) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            with open(f) as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            return block_from_items([{"text": ln} for ln in lines])
        return read
    return [make(f) for f in files]


def binary_tasks(paths) -> List[ReadTask]:
    """One row per file: {'path', 'bytes'} (reference:
    read_binary_files)."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            with open(f, "rb") as fh:
                data = fh.read()
            return block_from_items([{"path": f, "bytes": data}])
        return read
    return [make(f) for f in files]


IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tiff",
                    ".webp")


def image_tasks(paths, size=None, mode: str = None,
                include_paths: bool = False) -> List[ReadTask]:
    """Decode image files into {'image': HxWxC uint8 array} rows
    (reference: read_api.py:792 read_images — PIL decode, optional
    resize/mode conversion, optional path column). Directories expand to
    their image files."""
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith(IMAGE_EXTENSIONS)]
    if not files:
        raise ValueError(f"no image files found under {paths!r}")

    def make(f: str) -> ReadTask:
        def read() -> Block:
            from PIL import Image

            with Image.open(f) as img:
                if mode:
                    img = img.convert(mode)
                if size:
                    # API takes (height, width) like the reference's
                    # read_images; PIL resize wants (width, height).
                    img = img.resize((size[1], size[0]))
                arr = np.asarray(img)
            # Tensor column (fixed-size list + shape metadata): HxWxC
            # arrays round-trip through block_to_numpy exactly.
            cols: Dict[str, Any] = {"image": arr[None]}
            if include_paths:
                cols["path"] = np.array([f])
            return block_from_numpy(cols)
        return read
    return [make(f) for f in files]


def numpy_file_tasks(paths, column: str = "data") -> List[ReadTask]:
    """One block per .npy file (reference: read_numpy)."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            arr = np.load(f)
            return block_from_numpy({column: arr})
        return read
    return [make(f) for f in files]


def tfrecord_tasks(paths) -> List[ReadTask]:
    """Parse TFRecord files of tf.train.Example into arrow blocks
    (reference: read_api.py read_tfrecords /
    _internal/datasource/tfrecords_datasource.py). Feature decoding
    follows the reference: bytes_list/float_list/int64_list; a feature
    with exactly one value becomes a scalar column, several values a
    list column. Gated on tensorflow (the wire format's Example proto
    lives there)."""
    files = _expand_paths(paths)

    def make(f: str) -> ReadTask:
        def read() -> Block:
            try:
                import tensorflow as tf
            except ImportError as e:
                raise ImportError(
                    "read_tfrecords requires tensorflow for the "
                    "tf.train.Example wire format") from e
            import pyarrow as pa

            columns: Dict[str, list] = {}
            rows = 0
            for raw in tf.data.TFRecordDataset([f]):
                ex = tf.train.Example()
                ex.ParseFromString(bytes(raw.numpy()))
                rows += 1
                for name, feat in ex.features.feature.items():
                    kind = feat.WhichOneof("kind")
                    if kind == "bytes_list":
                        vals = list(feat.bytes_list.value)
                    elif kind == "float_list":
                        vals = list(feat.float_list.value)
                    elif kind == "int64_list":
                        vals = list(feat.int64_list.value)
                    else:
                        vals = []
                    col = columns.setdefault(name, [None] * (rows - 1))
                    # Empty feature = null (the wire format cannot
                    # distinguish an empty list from a missing value;
                    # write_tfrecords emits empty features for None) —
                    # keeping [] here would force the whole column to
                    # list type and break scalar unwrapping.
                    col.append(vals if vals else None)
                for name, col in columns.items():
                    if len(col) < rows:
                        col.append(None)  # feature absent in this record
            # Column shape is decided PER COLUMN over the whole FILE:
            # unwrapping only single-value rows would mix scalars and
            # lists in one column (ArrowInvalid) when lengths vary.
            # (The Example wire format drops the scalar/list
            # distinction, so a file whose every value has length 1
            # necessarily reads back as scalars — same ambiguity as the
            # reference's tfrecords datasource.)
            out = {}
            for name, col in columns.items():
                if all(v is None or len(v) == 1 for v in col):
                    out[name] = [None if v is None else v[0] for v in col]
                else:
                    out[name] = col
            return pa.table(out)
        return read
    return [make(f) for f in files]


def row_to_tf_example(row: Dict[str, Any]):
    """One dataset row -> tf.train.Example (write_tfrecords helper)."""
    import tensorflow as tf

    feats = {}
    for name, value in row.items():
        if isinstance(value, (list, tuple, np.ndarray)):
            vals = [v for v in value if v is not None]
        elif value is None:
            vals = []  # nullable column -> empty feature
        else:
            vals = [value]
        if not vals:
            feats[name] = tf.train.Feature()
        elif isinstance(vals[0], bytes):
            feats[name] = tf.train.Feature(
                bytes_list=tf.train.BytesList(value=vals))
        elif isinstance(vals[0], str):
            feats[name] = tf.train.Feature(
                bytes_list=tf.train.BytesList(
                    value=[v.encode() for v in vals]))
        elif isinstance(vals[0], (int, np.integer, bool, np.bool_)):
            feats[name] = tf.train.Feature(
                int64_list=tf.train.Int64List(
                    value=[int(v) for v in vals]))
        else:
            feats[name] = tf.train.Feature(
                float_list=tf.train.FloatList(
                    value=[float(v) for v in vals]))
    return tf.train.Example(
        features=tf.train.Features(feature=feats))
