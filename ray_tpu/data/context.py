"""DataContext — execution knobs (reference python/ray/data/context.py)."""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    max_tasks_in_flight: int = 16
    default_batch_format: str = "numpy"
    actor_pool_size: int = 2
    verbose_progress: bool = False

    _local = threading.local()

    @classmethod
    def get_current(cls) -> "DataContext":
        ctx = getattr(cls._local, "ctx", None)
        if ctx is None:
            ctx = cls()
            cls._local.ctx = ctx
        return ctx
