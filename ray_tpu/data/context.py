"""DataContext — execution knobs (reference python/ray/data/context.py)."""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    max_tasks_in_flight: int = 16
    # Global streaming-execution byte budget: completed-but-unconsumed
    # operator outputs + running-task estimates (reference:
    # ResourceManager object-store memory budget).
    max_inflight_bytes: int = 256 * 1024 * 1024
    # Fraction of the budget reserved per-op (equal split); the rest is a
    # shared pool (reference: ReservationOpResourceAllocator).
    reservation_ratio: float = 0.5
    default_block_size_estimate: int = 1 * 1024 * 1024
    default_batch_format: str = "numpy"
    actor_pool_size: int = 2
    verbose_progress: bool = False
    # Stats of the most recent streaming execution (ExecutionStats).
    last_execution_stats: object = None

    _local = threading.local()

    @classmethod
    def get_current(cls) -> "DataContext":
        ctx = getattr(cls._local, "ctx", None)
        if ctx is None:
            ctx = cls()
            cls._local.ctx = ctx
        return ctx
