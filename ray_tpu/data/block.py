"""Block — the unit of data movement. Arrow-backed.

Reference: python/ray/data/block.py (+ _internal/arrow_block.py): a block
is an immutable pyarrow.Table shipped by ObjectRef between operators;
accessors convert to/from rows, numpy, pandas, and build batches.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
BatchFormat = Union[str]  # "numpy" | "pandas" | "pyarrow" | "rows"


def block_from_items(items: List[Any]) -> Block:
    if items and isinstance(items[0], dict):
        cols: Dict[str, List[Any]] = {k: [] for k in items[0]}
        for row in items:
            for k in cols:
                cols[k].append(row.get(k))
        return pa.table(cols)
    return pa.table({"item": list(items)})


def block_from_numpy(arrays: Dict[str, np.ndarray]) -> Block:
    import json

    cols = {}
    fields = []
    for k, v in arrays.items():
        v = np.asarray(v)
        if v.ndim <= 1:
            arr = pa.array(v)
            fields.append(pa.field(k, arr.type))
        else:
            # tensor column: flattened fixed-size list + the element shape
            # in field metadata so >2-D tensors round-trip exactly
            flat = v.reshape(len(v), -1)
            arr = pa.FixedSizeListArray.from_arrays(
                pa.array(flat.reshape(-1)), flat.shape[1])
            fields.append(pa.field(
                k, arr.type,
                metadata={b"tensor_shape":
                          json.dumps(list(v.shape[1:])).encode()}))
        cols[k] = arr
    return pa.table(cols, schema=pa.schema(fields))


def block_from_pandas(df) -> Block:
    return pa.Table.from_pandas(df, preserve_index=False)


def block_num_rows(block: Block) -> int:
    return block.num_rows


def block_size_bytes(block: Block) -> int:
    return block.nbytes


def block_slice(block: Block, start: int, end: int) -> Block:
    return block.slice(start, end - start)


def block_to_rows(block: Block) -> List[Dict[str, Any]]:
    import json

    rows = block.to_pylist()
    # Tensor columns (fixed-size list + shape metadata) flatten in
    # to_pylist; restore each row's element to its real ndarray shape so
    # row-level consumers (take/iter_rows/write_webdataset) see tensors,
    # not flat lists.
    shapes = {}
    for field in block.schema:
        meta = field.metadata or {}
        if b"tensor_shape" in meta:
            shapes[field.name] = tuple(json.loads(meta[b"tensor_shape"]))
    if shapes:
        for row in rows:
            for name, shape in shapes.items():
                v = row.get(name)
                if v is not None:
                    row[name] = np.asarray(v).reshape(shape)
    return rows


def block_to_numpy(block: Block) -> Dict[str, np.ndarray]:
    import json

    out = {}
    for i, name in enumerate(block.column_names):
        col = block.column(name)
        if pa.types.is_fixed_size_list(col.type):
            width = col.type.list_size
            flat = col.combine_chunks().flatten().to_numpy(
                zero_copy_only=False)
            meta = block.schema.field(i).metadata or {}
            if b"tensor_shape" in meta:
                shape = json.loads(meta[b"tensor_shape"])
                out[name] = flat.reshape(block.num_rows, *shape)
            else:
                out[name] = flat.reshape(block.num_rows, width)
        else:
            out[name] = col.to_numpy(zero_copy_only=False)
    return out


def block_to_pandas(block: Block):
    return block.to_pandas()


def concat_blocks(blocks: List[Block]) -> Block:
    # Drop schema-less empty placeholders so they can't poison promotion.
    blocks = [b for b in blocks
              if b is not None and (b.num_rows > 0 or b.column_names)]
    if not blocks:
        return pa.table({})
    nonempty = [b for b in blocks if b.num_rows > 0]
    if not nonempty:
        return blocks[0]
    return pa.concat_tables(nonempty, promote_options="default")


def format_batch(block: Block, batch_format: str):
    if batch_format in ("numpy", "np", "default"):
        return block_to_numpy(block)
    if batch_format in ("pandas", "pd"):
        return block_to_pandas(block)
    if batch_format in ("pyarrow", "arrow"):
        return block
    if batch_format == "rows":
        return block_to_rows(block)
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_block(batch) -> Block:
    """Normalize a UDF's output batch back into a Block."""
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return block_from_numpy(
            {k: np.asarray(v) for k, v in batch.items()})
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return block_from_pandas(batch)
    except ImportError:
        pass
    if isinstance(batch, list):
        return block_from_items(batch)
    raise TypeError(f"cannot convert batch of type {type(batch)} to block")


def iter_block_batches(block: Block, batch_size: Optional[int],
                       batch_format: str) -> Iterator[Any]:
    if batch_size is None or batch_size >= block.num_rows:
        if block.num_rows:
            yield format_batch(block, batch_format)
        return
    for start in range(0, block.num_rows, batch_size):
        yield format_batch(
            block.slice(start, min(batch_size, block.num_rows - start)),
            batch_format)
