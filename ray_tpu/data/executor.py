"""Streaming executor.

Reference: python/ray/data/_internal/execution/streaming_executor.py:48 —
operators run as remote tasks over Block ObjectRefs with bounded
in-flight tasks (backpressure); consecutive map stages are fused into one
task (the reference's fusion optimizer rule); all-to-all stages
materialize their input frontier then fan back out. Per-operator budgets
and stats live in ray_tpu/data/resource_manager.py (the reference's
ResourceManager/ReservationOpResourceAllocator); actor-pool stages scale
between a (min, max) size with demand
(reference: .../execution/autoscaler/).

The TPU angle: this engine is deliberately host-side (CPU) — it feeds
per-host train workers via streaming_split iterators; device transfer
happens in the consumer (SURVEY.md §2.4 'elastic/data-pipeline
parallelism' row).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, concat_blocks
from ray_tpu.data.context import DataContext
from ray_tpu.data.resource_manager import (ExecutionStats, OpStats,
                                           ResourceManager)


@dataclasses.dataclass
class MapStage:
    name: str
    fn: Callable[[Block], Block]          # pure block transform
    # "tasks" or ("actors", size, cls_factory); size int or (min, max)
    compute: Any = "tasks"
    # Row-count preserving (Dataset.map and friends): enables the
    # limit-pushdown optimizer rule (reference: logical optimizer rules
    # beyond fusion, _internal/logical/optimizers.py).
    preserves_rows: bool = False
    # fn is called as fn(block, ordinal) with the block's 0-based input
    # ordinal within this stage — deterministic per execution, which
    # lets seeded per-block RNG (random_sample) draw independent streams
    # without coordination or content hashing.
    wants_index: bool = False


@dataclasses.dataclass
class AllToAllStage:
    """Custom exchange: fn(list-of-blocks) -> list-of-blocks, executed in
    ONE remote streaming task (blocks never land in the driver). Built-in
    shuffles use the two-phase ShuffleStage instead."""

    name: str
    fn: Callable[[List[Block]], List[Block]]


@dataclasses.dataclass
class ShuffleStage:
    """Distributed two-phase exchange (reference: the exchange task
    graphs in python/ray/data/_internal/planner/exchange/ —
    sort_task_spec.py, shuffle_task_spec.py): map tasks partition each
    input block into R parts, reduce tasks merge the r-th part of every
    map. The driver only routes ObjectRefs."""

    name: str
    kind: str                       # "repartition" | "shuffle" | "sort"
    num_outputs: Optional[int] = None  # None → len(input blocks)
    key: Optional[str] = None       # sort key
    descending: bool = False
    seed: Optional[int] = None


@dataclasses.dataclass
class LimitStage:
    """Streaming row limit: stops pulling upstream once n rows are out."""

    n: int

    @property
    def name(self) -> str:
        return f"Limit({self.n})"


Stage = Any  # MapStage | AllToAllStage | LimitStage


def _push_down_limits(stages: List[Stage]) -> List[Stage]:
    """Optimizer rule (reference: logical/optimizers.py limit pushdown):
    a Limit hops BEFORE row-preserving map stages so the rows it would
    discard are never transformed; adjacent limits collapse to the min.
    Repartition is row-preserving too, but the limit must stay AFTER it
    only if output block-count matters — rows don't change, so the limit
    also hops over repartition-kind shuffles (not sorts: a limit after a
    sort selects DIFFERENT rows than before it)."""
    out: List[Stage] = []
    for st in stages:
        if isinstance(st, LimitStage):
            n = st.n
            hopped: List[Stage] = []
            while out:
                prev = out[-1]
                if isinstance(prev, LimitStage):
                    n = min(n, prev.n)
                    out.pop()
                elif isinstance(prev, MapStage) and prev.preserves_rows:
                    hopped.append(out.pop())
                elif isinstance(prev, ShuffleStage) and \
                        prev.kind == "repartition":
                    hopped.append(out.pop())
                else:
                    break
            out.append(LimitStage(n))
            out.extend(reversed(hopped))
            continue
        out.append(st)
    return out


def _drop_redundant_shuffles(stages: List[Stage]) -> List[Stage]:
    """Optimizer rule: consecutive repartitions — only the last one's
    output layout survives, so earlier ones are wasted exchanges."""
    out: List[Stage] = []
    for st in stages:
        if (isinstance(st, ShuffleStage) and st.kind == "repartition" and
                out and isinstance(out[-1], ShuffleStage) and
                out[-1].kind == "repartition"):
            out.pop()
        out.append(st)
    return out


def _fuse(stages: List[Stage]) -> List[Stage]:
    """Logical optimization: limit pushdown + redundant-shuffle
    elimination + fusion of adjacent task-compute MapStages (reference:
    _internal/logical/optimizers.py rule chain)."""
    stages = _drop_redundant_shuffles(_push_down_limits(stages))
    fused: List[Stage] = []
    for st in stages:
        if (isinstance(st, MapStage) and st.compute == "tasks" and fused
                and isinstance(fused[-1], MapStage)
                and fused[-1].compute == "tasks"):
            prev = fused.pop()

            def composed(block, idx=None, f1=prev.fn, f2=st.fn,
                         w1=prev.wants_index, w2=st.wants_index):
                mid = f1(block, idx) if w1 else f1(block)
                return f2(mid, idx) if w2 else f2(mid)

            fused.append(MapStage(
                f"{prev.name}->{st.name}", composed,
                preserves_rows=prev.preserves_rows and st.preserves_rows,
                wants_index=prev.wants_index or st.wants_index))
        else:
            fused.append(st)
    return fused


@ray_tpu.remote(num_returns="streaming")
def _exec_read(read_task, target_bytes: int):
    """Streaming read: yields blocks as the read produces them (reference:
    read tasks as streaming generators — the caller's first block is
    consumable before this task finishes). Oversized blocks are split to
    ~target_bytes chunks so downstream parallelism isn't lost."""
    out = read_task()
    blocks = [out] if isinstance(out, Block) else out
    for block in blocks:
        nbytes = block.nbytes
        if nbytes > target_bytes and block.num_rows > 1:
            n_chunks = min(block.num_rows,
                           -(-nbytes // max(target_bytes, 1)))
            rows_per = -(-block.num_rows // n_chunks)
            for s in range(0, block.num_rows, rows_per):
                yield block.slice(s, min(rows_per, block.num_rows - s))
        else:
            yield block


@ray_tpu.remote
def _exec_map(fn, block: Block) -> Block:
    return fn(block)


@ray_tpu.remote
def _exec_map_idx(fn, block: Block, idx: int) -> Block:
    return fn(block, idx)


@ray_tpu.remote
def _block_rows(block: Block) -> int:
    return block.num_rows


@ray_tpu.remote
def _slice_block(block: Block, start: int, length: int) -> Block:
    return block.slice(start, length)


@ray_tpu.remote(num_returns="streaming")
def _exec_exchange(fn, *blocks):
    """Custom all-to-all runs in one worker, streaming its outputs."""
    for out in fn(list(blocks)):
        yield out


@ray_tpu.remote
def _sample_keys(block: Block, key: str, k: int):
    """Sort sampling (reference: SortTaskSpec.sample_boundaries)."""
    import numpy as np

    col = block.column(key).drop_null().to_numpy(zero_copy_only=False)
    if len(col) == 0:
        return np.array([])
    idx = np.random.RandomState(0).choice(
        len(col), size=min(k, len(col)), replace=False)
    return col[idx]


@ray_tpu.remote
def _shuffle_map(block: Block, kind: str, num_reducers: int,
                 key, boundaries, seed, map_index: int):
    """Map side of the exchange: split one block into num_reducers parts.

    boundaries: sort → key cut points; repartition → this block's global
    row start + the global reducer row edges (order-preserving split).
    """
    import numpy as np

    n = block.num_rows
    if kind == "sort":
        # Partition ascending by the sampled boundaries. Null rows are
        # routed to whichever partition ends up LAST in the global output
        # (ascending: the last partition; descending: partition 0, since
        # reducer order is reversed) so nulls always sort to the end.
        descending = boundaries[0]
        boundaries = boundaries[1]
        sorted_block = block.sort_by([(key, "ascending")])
        arr = sorted_block.column(key)
        n_valid = len(arr) - arr.null_count
        valid = arr.drop_null().to_numpy(zero_copy_only=False)
        cuts = list(np.searchsorted(valid, boundaries, side="right")) \
            if len(boundaries) else []
        cuts += [n_valid] * (num_reducers - 1 - len(cuts))  # degenerate
        edges = [0, *cuts, n_valid]
        parts = [sorted_block.slice(edges[i], edges[i + 1] - edges[i])
                 for i in range(num_reducers)]
        if n_valid < n:
            nulls = sorted_block.slice(n_valid, n - n_valid)
            tail = 0 if descending else num_reducers - 1
            parts[tail] = concat_blocks([parts[tail], nulls]) \
                if parts[tail].num_rows else nulls
    elif kind == "shuffle":
        rng = np.random.RandomState(
            None if seed is None else (seed + 31 * map_index) % (2 ** 31))
        assign = rng.randint(0, num_reducers, size=n)
        parts = [block.take(np.nonzero(assign == r)[0])
                 for r in range(num_reducers)]
    elif kind == "hash":
        # Group-complete partitioning: every row of a key lands on the
        # same reducer (map_groups). pandas' hash is process-stable.
        import pandas as pd

        col = block.column(key).to_pandas()
        assign = (pd.util.hash_pandas_object(col, index=False)
                  .to_numpy() % num_reducers).astype(np.int64)
        parts = [block.take(np.nonzero(assign == r)[0])
                 for r in range(num_reducers)]
    else:  # repartition: order-preserving global-contiguous split
        global_start, reducer_edges = boundaries
        gs, ge = global_start, global_start + n
        parts = []
        for r in range(num_reducers):
            lo = max(gs, reducer_edges[r])
            hi = min(ge, reducer_edges[r + 1])
            parts.append(block.slice(lo - gs, max(hi - lo, 0)))
    return parts[0] if num_reducers == 1 else tuple(parts)


@ray_tpu.remote
def _shuffle_reduce(kind: str, key, descending: bool, seed,
                    reduce_index: int, *parts):
    """Reduce side: merge the reduce_index-th part of every map."""
    import numpy as np

    merged = concat_blocks([p for p in parts if p.num_rows]) \
        if any(p.num_rows for p in parts) else parts[0]
    if kind == "sort" and merged.num_rows:
        order = "descending" if descending else "ascending"
        merged = merged.sort_by([(key, order)])
    elif kind == "shuffle" and merged.num_rows:
        rng = np.random.RandomState(
            None if seed is None else (seed + 17 * reduce_index + 7) %
            (2 ** 31))
        merged = merged.take(rng.permutation(merged.num_rows))
    return merged


@ray_tpu.remote
class _MapActor:
    """Actor-pool worker for class-based UDFs (stateful map_batches)."""

    def __init__(self, cls_factory):
        self._callable = cls_factory()

    def apply(self, fn, block: Block) -> Block:
        return fn(self._callable, block)

    def apply_idx(self, fn, block: Block, idx: int) -> Block:
        return fn(self._callable, block, idx)


def _ref_size_bytes(ref) -> Optional[int]:
    """Best-effort serialized size of a locally-known object (inline
    memory-store objects only — no fetch, no pin)."""
    try:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is None:
            return None
        data = w.core.memory_store.get_if_exists(ref.id)
        return len(data) if data is not None else None
    except Exception:
        return None


class _OpDriver:
    """Shared submission/backpressure logic for one operator's stream."""

    def __init__(self, rm: ResourceManager, stats: OpStats,
                 default_estimate: int):
        self.rm = rm
        self.stats = stats
        self.name = stats.name
        self._estimate = default_estimate  # EMA of observed block bytes
        self._t0 = time.perf_counter()

    def wait_for_budget(self, in_flight: collections.deque,
                        on_head_done=None) -> Iterator:
        """Yields completed heads until a new task may be submitted."""
        while not self.rm.can_submit(self.name, self._estimate):
            if not in_flight:
                return  # idle op: liveness rule admits the next submit
            t0 = time.perf_counter()
            head, est = in_flight.popleft()
            ray_tpu.wait([head], num_returns=1)
            self.stats.time_blocked_s += time.perf_counter() - t0
            if on_head_done is not None:
                on_head_done(head)
            yield self.finish(head, est)

    def submitted(self, in_flight: collections.deque, ref) -> None:
        self.rm.on_task_submitted(self.name, self._estimate)
        in_flight.append((ref, self._estimate))

    def finish(self, ref, estimate: int):
        actual = _ref_size_bytes(ref)
        self.rm.on_task_finished(self.name, estimate, actual)
        return ref, self._account_block(actual, estimate)

    def item_produced(self, ref) -> int:
        """One streamed item landed; returns the bytes charged for it."""
        actual = _ref_size_bytes(ref)
        held = self._account_block(actual, self._estimate)
        self.rm.on_output_produced(self.name, held)
        return held

    def _account_block(self, actual: Optional[int], estimate: int) -> int:
        held = actual if actual is not None else estimate
        if actual is not None:
            self._estimate = int(0.7 * self._estimate + 0.3 * actual)
        self.stats.blocks_out += 1
        self.stats.bytes_out += held
        return held

    def consumed(self, bytes_held: int) -> None:
        self.rm.on_output_consumed(self.name, bytes_held)

    def done(self) -> None:
        self.stats.wall_time_s = time.perf_counter() - self._t0


class StreamingExecutor:
    def __init__(self, context: Optional[DataContext] = None):
        self.context = context or DataContext.get_current()
        self.last_stats: Optional[ExecutionStats] = None

    # ------------------------------------------------------------------
    def execute(self, read_tasks: List[Callable[[], Block]],
                stages: List[Stage]) -> Iterator[Any]:
        """Yields Block ObjectRefs in completion order (streaming)."""
        ctx = self.context
        stages = _fuse(list(stages))
        rm = ResourceManager(
            max_tasks=ctx.max_tasks_in_flight * max(
                1, 1 + sum(1 for s in stages if isinstance(s, MapStage))),
            max_bytes=ctx.max_inflight_bytes,
            reservation_ratio=ctx.reservation_ratio)
        t_start = time.perf_counter()
        # Split pipeline at barriers (all-to-all) / stream-truncators.
        segments: List[Tuple[List[MapStage], Optional[Stage]]] = []
        cur: List[MapStage] = []
        for st in stages:
            if isinstance(st, (AllToAllStage, ShuffleStage, LimitStage)):
                segments.append((cur, st))
                cur = []
            else:
                cur.append(st)
        segments.append((cur, None))

        source: Iterator[Any] = self._stream_source(read_tasks, rm)
        for map_stages, boundary in segments:
            for st in map_stages:
                source = self._stream_one(source, st, rm)
            if isinstance(boundary, LimitStage):
                source = self._stream_limit(source, boundary.n)
            elif isinstance(boundary, ShuffleStage):
                source = self._execute_shuffle(boundary, source, rm)
            elif boundary is not None:
                # Custom exchange: one remote streaming task; the driver
                # only forwards refs.
                refs = list(source)
                source = iter(_exec_exchange.remote(boundary.fn, *refs))

        def finalize(src):
            try:
                for ref in src:
                    yield ref
            finally:
                stats = ExecutionStats(
                    rm.all_stats(), time.perf_counter() - t_start)
                self.last_stats = stats
                DataContext.get_current().last_execution_stats = stats

        return finalize(source)

    def _execute_shuffle(self, spec: ShuffleStage, source: Iterator[Any],
                         rm: ResourceManager) -> Iterator[Any]:
        """Two-phase distributed exchange over ObjectRefs: map-side
        partition then reduce-side merge; no block ever lands in the
        driver (reference: _internal/planner/exchange/)."""
        import numpy as np

        map_stats = rm.register_op(f"{spec.name}:map")
        red_stats = rm.register_op(f"{spec.name}:reduce")
        refs = list(source)  # barrier: all-to-all needs the full frontier
        if not refs:
            return iter(())
        n_reducers = max(1, len(refs) if spec.num_outputs is None
                         else spec.num_outputs)

        if spec.kind == "sort":
            boundaries: Any = []
            samples = ray_tpu.get(
                [_sample_keys.remote(r, spec.key, 32) for r in refs])
            pool = np.sort(np.concatenate(
                [s for s in samples if len(s)] or [np.array([])]))
            if len(pool) and n_reducers > 1:
                q = [len(pool) * (i + 1) // n_reducers
                     for i in range(n_reducers - 1)]
                boundaries = pool[np.minimum(q, len(pool) - 1)].tolist()
            per_map_boundaries = [(spec.descending, boundaries)] * len(refs)
        elif spec.kind == "repartition":
            # Order-preserving split needs each map's global row offset
            # and the global reducer edges (counts are tiny ints).
            counts = ray_tpu.get([_block_rows.remote(r) for r in refs])
            total = sum(counts)
            base, rem = divmod(total, n_reducers)
            edges = [0]
            for r in range(n_reducers):
                edges.append(edges[-1] + base + (1 if r < rem else 0))
            starts = []
            acc = 0
            for c in counts:
                starts.append(acc)
                acc += c
            per_map_boundaries = [(s, edges) for s in starts]
        else:
            per_map_boundaries = [None] * len(refs)

        maps = []
        for m, ref in enumerate(refs):
            out = _shuffle_map.options(num_returns=n_reducers).remote(
                ref, spec.kind, n_reducers, spec.key,
                per_map_boundaries[m], spec.seed, m)
            maps.append([out] if n_reducers == 1 else out)
        out_refs = []
        for r in range(n_reducers):
            out_refs.append(_shuffle_reduce.remote(
                spec.kind, spec.key, spec.descending, spec.seed, r,
                *[parts[r] for parts in maps]))
        if spec.kind == "sort" and spec.descending:
            out_refs.reverse()
        # Informational stats, finalized here: all tasks are already
        # submitted and will run even if a downstream limit stops
        # consuming the outputs early.
        map_stats.tasks_submitted = map_stats.tasks_finished = len(maps)
        map_stats.blocks_out = len(maps) * n_reducers
        red_stats.tasks_submitted = red_stats.tasks_finished = n_reducers
        red_stats.blocks_out = n_reducers
        return iter(out_refs)

    @staticmethod
    def _stream_limit(source: Iterator[Any], n: int) -> Iterator[Any]:
        """Early-exit: stops consuming `source` (and thus all upstream task
        submission) once n rows have been yielded. Row counting and the
        final partial slice run as remote tasks — blocks stay off the
        driver."""
        seen = 0
        for ref in source:
            if seen >= n:
                break
            rows = ray_tpu.get(_block_rows.remote(ref))
            take = min(rows, n - seen)
            seen += take
            if take == rows:
                yield ref
            else:
                yield _slice_block.remote(ref, 0, take)
            if seen >= n:
                break

    # ------------------------------------------------------------------
    def _stream_source(self, read_tasks, rm: ResourceManager
                       ) -> Iterator[Any]:
        # Read tasks are streaming generators: each yielded block's ref is
        # handed downstream the moment the item report lands — the first
        # block of a read task is consumable before the task finishes.
        # Blocks are yielded in task-SUBMISSION order (the reference's
        # default preserve_order semantics): only the head stream is
        # waited on, so later tasks still execute concurrently behind it.
        # Memory bounding: byte accounting here covers consumed (head)
        # items; runahead of the non-head streams is bounded by the
        # producer-side backpressure window
        # (config.streaming_backpressure_num_items per stream).
        op = _OpDriver(rm, rm.register_op("Read"),
                       self.context.default_block_size_estimate)
        limit = self.context.max_tasks_in_flight
        target = self.context.target_max_block_size
        pending = collections.deque(read_tasks)
        streams: collections.deque = collections.deque()
        try:
            while pending or streams:
                while pending and len(streams) < limit and \
                        (not streams or rm.can_submit(op.name,
                                                      op._estimate)):
                    rm.on_task_submitted(op.name, op._estimate)
                    streams.append(
                        (_exec_read.remote(pending.popleft(), target),
                         op._estimate))
                head, est = streams[0]
                t0 = time.perf_counter()
                try:
                    ref = head.next()
                except StopIteration:
                    streams.popleft()
                    rm.on_task_finished(op.name, est, 0)
                    continue
                finally:
                    op.stats.time_blocked_s += time.perf_counter() - t0
                held = op.item_produced(ref)
                yield ref
                op.consumed(held)
        finally:
            op.done()

    def _stream_one(self, source: Iterator[Any],
                    stage: MapStage, rm: ResourceManager) -> Iterator[Any]:
        op = _OpDriver(rm, rm.register_op(stage.name),
                       self.context.default_block_size_estimate)
        limit = self.context.max_tasks_in_flight
        if stage.compute == "tasks":
            try:
                in_flight: collections.deque = collections.deque()
                for i, ref in enumerate(source):
                    for done_ref, held in op.wait_for_budget(in_flight):
                        yield done_ref
                        op.consumed(held)
                    op.submitted(
                        in_flight,
                        _exec_map_idx.remote(stage.fn, ref, i)
                        if stage.wants_index
                        else _exec_map.remote(stage.fn, ref))
                    if len(in_flight) >= limit:
                        head, est = in_flight.popleft()
                        ray_tpu.wait([head], num_returns=1)
                        out, held = op.finish(head, est)
                        yield out
                        op.consumed(held)
                while in_flight:
                    head, est = in_flight.popleft()
                    ray_tpu.wait([head], num_returns=1)
                    out, held = op.finish(head, est)
                    yield out
                    op.consumed(held)
            finally:
                op.done()
            return

        # ---- actor pool (possibly autoscaling between (min, max)) ----
        _, size, cls_factory = stage.compute
        if isinstance(size, (tuple, list)):
            min_size, max_size = int(size[0]), int(size[1])
        else:
            min_size = max_size = int(size)
        pool: Dict[Any, int] = {
            _MapActor.remote(cls_factory): 0 for _ in range(min_size)}
        op.stats.actor_pool_size = len(pool)

        def least_loaded():
            return min(pool, key=pool.get)

        def maybe_autoscale(backlog: int) -> None:
            # Scale up when every actor has >1 queued task; scale down
            # (idle actors only) when half the pool would suffice.
            if backlog > 2 * len(pool) and len(pool) < max_size:
                pool[_MapActor.remote(cls_factory)] = 0
                op.stats.actor_pool_scaleups = getattr(
                    op.stats, "actor_pool_scaleups", 0) + 1
            elif len(pool) > min_size and backlog < len(pool) // 2:
                for actor, n in list(pool.items()):
                    if n == 0 and len(pool) > min_size:
                        del pool[actor]
                        try:
                            ray_tpu.kill(actor)
                        except Exception:
                            pass
                        break
            op.stats.actor_pool_size = max(
                getattr(op.stats, "actor_pool_size", 0), len(pool))

        ref_actor: Dict[int, Any] = {}  # id(ref) -> executing actor

        def head_done(head) -> None:
            a = ref_actor.pop(id(head), None)
            if a is not None and a in pool:
                pool[a] -= 1

        try:
            in_flight = collections.deque()
            for i, ref in enumerate(source):
                for done_ref, held in op.wait_for_budget(in_flight,
                                                         head_done):
                    yield done_ref
                    op.consumed(held)
                maybe_autoscale(len(in_flight))
                actor = least_loaded()
                pool[actor] += 1
                out = (actor.apply_idx.remote(stage.fn, ref, i)
                       if stage.wants_index
                       else actor.apply.remote(stage.fn, ref))
                ref_actor[id(out)] = actor
                op.submitted(in_flight, out)
                if len(in_flight) >= limit:
                    head, est = in_flight.popleft()
                    ray_tpu.wait([head], num_returns=1)
                    head_done(head)
                    out2, held = op.finish(head, est)
                    yield out2
                    op.consumed(held)
            while in_flight:
                head, est = in_flight.popleft()
                ray_tpu.wait([head], num_returns=1)
                head_done(head)
                out2, held = op.finish(head, est)
                yield out2
                op.consumed(held)
        finally:
            op.done()
            for a in pool:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
