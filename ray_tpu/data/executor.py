"""Streaming executor.

Reference: python/ray/data/_internal/execution/streaming_executor.py:48 —
operators run as remote tasks over Block ObjectRefs with bounded
in-flight tasks (backpressure); consecutive map stages are fused into one
task (the reference's fusion optimizer rule); all-to-all stages
materialize their input frontier then fan back out.

The TPU angle: this engine is deliberately host-side (CPU) — it feeds
per-host train workers via streaming_split iterators; device transfer
happens in the consumer (SURVEY.md §2.4 'elastic/data-pipeline
parallelism' row).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, concat_blocks
from ray_tpu.data.context import DataContext


@dataclasses.dataclass
class MapStage:
    name: str
    fn: Callable[[Block], Block]          # pure block transform
    # "tasks" or ("actors", pool_size, cls_factory)
    compute: Any = "tasks"


@dataclasses.dataclass
class AllToAllStage:
    name: str
    # driver-side: takes materialized blocks, returns new block list
    fn: Callable[[List[Block]], List[Block]]


@dataclasses.dataclass
class LimitStage:
    """Streaming row limit: stops pulling upstream once n rows are out."""

    n: int

    @property
    def name(self) -> str:
        return f"Limit({self.n})"


Stage = Any  # MapStage | AllToAllStage | LimitStage


def _fuse(stages: List[Stage]) -> List[Stage]:
    """Fuse runs of task-compute MapStages into single stages."""
    fused: List[Stage] = []
    for st in stages:
        if (isinstance(st, MapStage) and st.compute == "tasks" and fused
                and isinstance(fused[-1], MapStage)
                and fused[-1].compute == "tasks"):
            prev = fused.pop()

            def composed(block, f1=prev.fn, f2=st.fn):
                return f2(f1(block))

            fused.append(MapStage(f"{prev.name}->{st.name}", composed))
        else:
            fused.append(st)
    return fused


@ray_tpu.remote
def _exec_read(read_task) -> Block:
    return read_task()


@ray_tpu.remote
def _exec_map(fn, block: Block) -> Block:
    return fn(block)


@ray_tpu.remote
class _MapActor:
    """Actor-pool worker for class-based UDFs (stateful map_batches)."""

    def __init__(self, cls_factory):
        self._callable = cls_factory()

    def apply(self, fn, block: Block) -> Block:
        return fn(self._callable, block)


class StreamingExecutor:
    def __init__(self, context: Optional[DataContext] = None):
        self.context = context or DataContext.get_current()

    # ------------------------------------------------------------------
    def execute(self, read_tasks: List[Callable[[], Block]],
                stages: List[Stage]) -> Iterator[Any]:
        """Yields Block ObjectRefs in completion order (streaming)."""
        stages = _fuse(list(stages))
        # Split pipeline at barriers (all-to-all) / stream-truncators.
        segments: List[Tuple[List[MapStage], Optional[Stage]]] = []
        cur: List[MapStage] = []
        for st in stages:
            if isinstance(st, (AllToAllStage, LimitStage)):
                segments.append((cur, st))
                cur = []
            else:
                cur.append(st)
        segments.append((cur, None))

        source: Iterator[Any] = self._stream_source(read_tasks)
        for map_stages, boundary in segments:
            source = self._stream_maps(source, map_stages)
            if isinstance(boundary, LimitStage):
                source = self._stream_limit(source, boundary.n)
            elif boundary is not None:
                blocks = [ray_tpu.get(r) for r in source]
                out_blocks = boundary.fn(blocks)
                source = iter([ray_tpu.put(b) for b in out_blocks])
        return source

    @staticmethod
    def _stream_limit(source: Iterator[Any], n: int) -> Iterator[Any]:
        """Early-exit: stops consuming `source` (and thus all upstream task
        submission) once n rows have been yielded."""
        seen = 0
        for ref in source:
            if seen >= n:
                break
            block = ray_tpu.get(ref)
            take = min(block.num_rows, n - seen)
            seen += take
            if take == block.num_rows:
                yield ref
            else:
                yield ray_tpu.put(block.slice(0, take))
            if seen >= n:
                break

    # ------------------------------------------------------------------
    def _stream_source(self, read_tasks) -> Iterator[Any]:
        # Blocks are yielded in task-SUBMISSION order (the reference's
        # default preserve_order semantics): only the head ref is waited
        # on, so later tasks still execute concurrently behind it.
        limit = self.context.max_tasks_in_flight
        pending = collections.deque(read_tasks)
        in_flight: collections.deque = collections.deque()
        while pending or in_flight:
            while pending and len(in_flight) < limit:
                in_flight.append(_exec_read.remote(pending.popleft()))
            head = in_flight.popleft()
            ray_tpu.wait([head], num_returns=1)
            yield head

    def _stream_maps(self, source: Iterator[Any],
                     map_stages: List[MapStage]) -> Iterator[Any]:
        for st in map_stages:
            source = self._stream_one(source, st)
        return source

    def _stream_one(self, source: Iterator[Any],
                    stage: MapStage) -> Iterator[Any]:
        limit = self.context.max_tasks_in_flight
        if stage.compute == "tasks":
            in_flight: collections.deque = collections.deque()
            for ref in source:
                in_flight.append(_exec_map.remote(stage.fn, ref))
                if len(in_flight) >= limit:
                    head = in_flight.popleft()
                    ray_tpu.wait([head], num_returns=1)
                    yield head
            while in_flight:
                head = in_flight.popleft()
                ray_tpu.wait([head], num_returns=1)
                yield head
        else:
            _, pool_size, cls_factory = stage.compute
            actors = [_MapActor.remote(cls_factory)
                      for _ in range(pool_size)]
            try:
                in_flight = collections.deque()
                i = 0
                for ref in source:
                    actor = actors[i % len(actors)]
                    i += 1
                    in_flight.append(actor.apply.remote(stage.fn, ref))
                    if len(in_flight) >= limit:
                        head = in_flight.popleft()
                        ray_tpu.wait([head], num_returns=1)
                        yield head
                while in_flight:
                    head = in_flight.popleft()
                    ray_tpu.wait([head], num_returns=1)
                    yield head
            finally:
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass
