"""Comet ML integration, gated on the ``comet_ml`` package.

Reference: python/ray/air/integrations/comet.py (CometLoggerCallback).
Same per-trial-experiment shape over this framework's Tune callback
seam; the dependency-free local tracker (tracking.py) is the in-tree
default when comet is absent.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.logger import LoggerCallback, _flatten


def _import_comet():
    try:
        import comet_ml
    except ImportError as e:
        raise ImportError(
            "comet_ml is not installed. `pip install comet-ml`, or use "
            "the dependency-free in-tree tracker: "
            "ray_tpu.air.integrations.setup_tracking / "
            "TrackingLoggerCallback") from e
    return comet_ml


class CometLoggerCallback(LoggerCallback):
    """Tune callback: one comet Experiment per trial."""

    def __init__(self, online: bool = True,
                 tags: Optional[List[str]] = None,
                 **experiment_kwargs):
        super().__init__()
        self._comet = _import_comet()
        self._online = online
        self._tags = list(tags or [])
        self._kwargs = experiment_kwargs
        self._experiments: Dict[str, Any] = {}

    def _exp_for(self, trial):
        exp = self._experiments.get(trial.trial_id)
        if exp is None:
            cls = (self._comet.Experiment if self._online
                   else self._comet.OfflineExperiment)
            exp = cls(**self._kwargs)
            exp.set_name(f"trial_{trial.trial_id}")
            exp.add_tags(self._tags)
            exp.log_parameters(_flatten(trial.config))
            self._experiments[trial.trial_id] = exp
        return exp

    def on_trial_start(self, trial) -> None:
        self._exp_for(trial)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        exp = self._exp_for(trial)
        step = result.get("training_iteration")
        metrics = {k: v for k, v in _flatten(result).items()
                   if isinstance(v, (int, float))
                   and not isinstance(v, bool)}
        exp.log_metrics(metrics, step=step)

    def on_trial_complete(self, trial) -> None:
        exp = self._experiments.pop(trial.trial_id, None)
        if exp is not None:
            exp.end()

    def on_experiment_end(self, trials: List) -> None:
        for exp in self._experiments.values():
            exp.end()
        self._experiments.clear()
