"""MLflow integration, gated on the ``mlflow`` package.

Reference: python/ray/air/integrations/mlflow.py:32 (``setup_mlflow``)
and :193 (``MLflowLoggerCallback``). Same two entry points, redesigned
over this framework's Tune callback seam; the dependency-free local
tracker (``tracking.py``) is the in-tree default when mlflow is absent.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.air.integrations.tracking import (_NoopModule,
                                               _train_world_rank)
from ray_tpu.tune.logger import LoggerCallback, _flatten


def _import_mlflow():
    try:
        import mlflow
    except ImportError as e:
        raise ImportError(
            "mlflow is not installed. `pip install mlflow`, or use the "
            "dependency-free in-tree tracker: "
            "ray_tpu.air.integrations.setup_tracking / "
            "TrackingLoggerCallback") from e
    return mlflow


def setup_mlflow(config: Optional[Dict[str, Any]] = None,
                 *,
                 tracking_uri: Optional[str] = None,
                 registry_uri: Optional[str] = None,
                 experiment_id: Optional[str] = None,
                 experiment_name: Optional[str] = None,
                 run_name: Optional[str] = None,
                 create_experiment_if_not_exists: bool = True,
                 tags: Optional[Dict[str, Any]] = None,
                 rank_zero_only: bool = True):
    """Initialize an mlflow session inside a trainable / train loop and
    return the configured ``mlflow`` module (reference contract:
    air/integrations/mlflow.py:32). Under Ray Train, non-rank-zero
    workers receive a no-op module so logging is not duplicated."""
    if rank_zero_only:
        rank = _train_world_rank()
        if rank is not None and rank != 0:
            return _NoopModule()
    mlflow = _import_mlflow()
    if tracking_uri:
        mlflow.set_tracking_uri(tracking_uri)
    if registry_uri and hasattr(mlflow, "set_registry_uri"):
        mlflow.set_registry_uri(registry_uri)
    if experiment_id is not None:
        mlflow.set_experiment(experiment_id=experiment_id)
    elif experiment_name is not None:
        exp = mlflow.get_experiment_by_name(experiment_name)
        if exp is None and create_experiment_if_not_exists:
            mlflow.create_experiment(experiment_name)
        mlflow.set_experiment(experiment_name)
    run = mlflow.start_run(run_name=run_name, nested=True)
    if tags:
        mlflow.set_tags(tags)
    if config:
        params = {k: v for k, v in _flatten(config).items()}
        if params:
            mlflow.log_params(params)
    return mlflow


class MLflowLoggerCallback(LoggerCallback):
    """Tune callback: one mlflow run per trial (reference:
    air/integrations/mlflow.py:193). Uses the low-level
    ``MlflowClient`` API with explicit run ids — the fluent
    ``start_run`` stack is process-global and interleaves when the
    controller runs many trials concurrently. Import is checked at
    construction so a missing dependency fails at Tuner build time,
    not mid-run."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 registry_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None,
                 tags: Optional[Dict[str, Any]] = None,
                 save_artifact: bool = False):
        super().__init__()
        mlflow = _import_mlflow()
        self._client = mlflow.tracking.MlflowClient(
            tracking_uri=tracking_uri, registry_uri=registry_uri)
        self._tags = dict(tags or {})
        self._save_artifact = save_artifact
        self._run_ids: Dict[str, str] = {}
        name = experiment_name or "ray_tpu"
        exp = self._client.get_experiment_by_name(name)
        self._experiment_id = (exp.experiment_id if exp is not None
                               else self._client.create_experiment(name))

    def on_trial_start(self, trial) -> None:
        tags = dict(self._tags)
        tags["trial_id"] = trial.trial_id
        tags["mlflow.runName"] = f"trial_{trial.trial_id}"
        run = self._client.create_run(self._experiment_id, tags=tags)
        run_id = run.info.run_id
        self._run_ids[trial.trial_id] = run_id
        for k, v in _flatten(trial.config).items():
            self._client.log_param(run_id, k, v)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        if trial.trial_id not in self._run_ids:
            self.on_trial_start(trial)
        run_id = self._run_ids[trial.trial_id]
        step = int(result.get("training_iteration", 0) or 0)
        for k, v in _flatten(result).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._client.log_metric(run_id, k, float(v), step=step)

    def on_trial_complete(self, trial) -> None:
        run_id = self._run_ids.pop(trial.trial_id, None)
        if run_id is None:
            return
        if self._save_artifact and getattr(trial, "checkpoint_path", None):
            try:
                self._client.log_artifacts(run_id, trial.checkpoint_path)
            except Exception:
                pass
        self._client.set_terminated(
            run_id, "FAILED" if trial.error else "FINISHED")

    def on_experiment_end(self, trials: List) -> None:
        for run_id in self._run_ids.values():
            self._client.set_terminated(run_id, "FINISHED")
        self._run_ids.clear()
