"""Weights & Biases integration, gated on the ``wandb`` package.

Reference: python/ray/air/integrations/wandb.py:63 (``setup_wandb``)
and :453 (``WandbLoggerCallback``). Redesigned over this framework's
Tune callback seam: the reference fans each trial's logging through a
dedicated logging actor; here the controller is already a single
process with per-trial callbacks, so runs are plain ``wandb.init``
handles kept per trial id.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from ray_tpu.air.integrations.tracking import (_NoopModule,
                                               _train_world_rank)
from ray_tpu.tune.logger import LoggerCallback, _flatten

WANDB_ENV_VAR = "WANDB_API_KEY"
WANDB_MODE_ENV_VAR = "WANDB_MODE"


def _import_wandb():
    try:
        import wandb
    except ImportError as e:
        raise ImportError(
            "wandb is not installed. `pip install wandb`, or use the "
            "dependency-free in-tree tracker: "
            "ray_tpu.air.integrations.setup_tracking / "
            "TrackingLoggerCallback") from e
    return wandb


def setup_wandb(config: Optional[Dict[str, Any]] = None,
                *,
                api_key: Optional[str] = None,
                project: Optional[str] = None,
                group: Optional[str] = None,
                name: Optional[str] = None,
                mode: Optional[str] = None,
                rank_zero_only: bool = True,
                **init_kwargs):
    """Initialize wandb inside a trainable / train loop and return the
    run handle (reference contract: air/integrations/wandb.py:63).
    Under Ray Train, non-rank-zero workers receive a no-op handle."""
    if rank_zero_only:
        rank = _train_world_rank()
        if rank is not None and rank != 0:
            return _NoopModule()
    wandb = _import_wandb()
    if api_key:
        os.environ[WANDB_ENV_VAR] = api_key
    if mode:
        os.environ[WANDB_MODE_ENV_VAR] = mode
    return wandb.init(project=project or "ray_tpu", group=group,
                      name=name, config=dict(config or {}),
                      **init_kwargs)


class WandbLoggerCallback(LoggerCallback):
    """Tune callback: one wandb run per trial (reference:
    air/integrations/wandb.py:453). Construction checks the import and
    credentials; each trial's run is created lazily on first event."""

    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None,
                 api_key: Optional[str] = None,
                 mode: Optional[str] = None,
                 excludes: Optional[List[str]] = None,
                 log_config: bool = True,
                 upload_checkpoints: bool = False,
                 **init_kwargs):
        super().__init__()
        self._wandb = _import_wandb()
        if api_key:
            os.environ[WANDB_ENV_VAR] = api_key
        if mode:
            os.environ[WANDB_MODE_ENV_VAR] = mode
        self._project = project or "ray_tpu"
        self._group = group
        self._excludes = set(excludes or [])
        self._log_config = log_config
        self._upload_checkpoints = upload_checkpoints
        self._init_kwargs = init_kwargs
        self._runs: Dict[str, Any] = {}

    def _run_for(self, trial):
        run = self._runs.get(trial.trial_id)
        if run is None:
            # reinit="create_new": concurrent trials each need their own
            # live run handle. Plain reinit=True FINISHES the previously
            # active run, so trial B's lazy init would kill trial A's
            # run mid-experiment (we log through the returned handle,
            # never the global wandb.log, so create_new is sufficient).
            run = self._wandb.init(
                project=self._project, group=self._group,
                name=f"trial_{trial.trial_id}", id=trial.trial_id,
                config=dict(trial.config) if self._log_config else None,
                reinit="create_new", resume="allow", **self._init_kwargs)
            self._runs[trial.trial_id] = run
        return run

    def on_trial_start(self, trial) -> None:
        self._run_for(trial)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        run = self._run_for(trial)
        step = result.get("training_iteration")
        metrics = {k: v for k, v in _flatten(result).items()
                   if k not in self._excludes
                   and isinstance(v, (int, float, str))
                   and not isinstance(v, bool)}
        run.log(metrics, step=int(step) if step is not None else None)

    def on_trial_complete(self, trial) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is None:
            return
        if self._upload_checkpoints and getattr(trial, "checkpoint_path",
                                                None):
            try:
                art = self._wandb.Artifact(
                    f"checkpoint_{trial.trial_id}", type="model")
                art.add_dir(trial.checkpoint_path)
                run.log_artifact(art)
            except Exception:
                pass
        run.finish(exit_code=1 if trial.error else 0)

    def on_experiment_end(self, trials: List) -> None:
        for run in self._runs.values():
            run.finish()
        self._runs.clear()
