"""Dependency-free local experiment tracker — the in-tree default.

Reference: the role of python/ray/air/integrations/mlflow.py:32
(``setup_mlflow``) / wandb.py:453 (``WandbLoggerCallback``) — but
instead of an external tracking server this backend is a plain
directory tree, so every deployment gets durable run history with zero
dependencies:

    <root>/<experiment>/<run_id>/
        meta.json       {run_name, experiment, start/end time, status}
        params.json     flat params dict
        metrics.jsonl   one JSON line per log_metrics() call (+ step/ts)
        tags.json       user tags

Two entry points, mirroring the reference's split:
- ``TrackingLoggerCallback`` — a Tune logger callback: one run per
  trial, params from trial.config, metrics from every reported result.
- ``setup_tracking()`` — imperative API for use INSIDE a training
  function (rank-zero gated under Train), returning a ``Run``.

``list_runs()`` + the ``ray_tpu runs`` CLI read the tree back.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.tune.logger import LoggerCallback, _flatten

_DEFAULT_ROOT = os.path.join("~", "ray_tpu_results", "tracking")


def _root(root: Optional[str]) -> str:
    return os.path.expanduser(
        root or os.environ.get("RAY_TPU_TRACKING_ROOT", _DEFAULT_ROOT))


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class Run:
    """One tracked run (analog of an mlflow run / wandb run object)."""

    def __init__(self, root: str, experiment: str, run_id: str,
                 run_name: str, resumed: bool = False):
        self.experiment = experiment
        self.run_id = run_id
        self.run_name = run_name
        self.dir = os.path.join(root, experiment, run_id)
        os.makedirs(self.dir, exist_ok=True)
        self._step = 0
        if not resumed or not os.path.exists(self._p("meta.json")):
            self._write("meta.json", {
                "run_id": run_id, "run_name": run_name,
                "experiment": experiment, "status": "RUNNING",
                "start_time": time.time(), "end_time": None,
            })

    def _p(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def _write(self, name: str, obj: dict) -> None:
        tmp = self._p(name + ".tmp")
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, default=str)
        os.replace(tmp, self._p(name))

    def _read(self, name: str) -> dict:
        try:
            with open(self._p(name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    # ---------------- logging API ----------------
    def log_params(self, params: Dict[str, Any]) -> None:
        merged = self._read("params.json")
        merged.update({k: _jsonable(v)
                       for k, v in _flatten(params).items()})
        self._write("params.json", merged)

    def log_metrics(self, metrics: Dict[str, Any],
                    step: Optional[int] = None) -> None:
        if step is None:
            step = self._step
        self._step = step + 1
        row = {"step": step, "ts": time.time()}
        for k, v in _flatten(metrics).items():
            if isinstance(v, bool):
                continue
            row[k] = v if isinstance(v, (int, float, str)) else _jsonable(v)
        with open(self._p("metrics.jsonl"), "a") as f:
            f.write(json.dumps(row, default=str) + "\n")

    def set_tags(self, tags: Dict[str, Any]) -> None:
        merged = self._read("tags.json")
        merged.update({k: _jsonable(v) for k, v in tags.items()})
        self._write("tags.json", merged)

    def finish(self, status: str = "FINISHED") -> None:
        meta = self._read("meta.json")
        meta["status"] = status
        meta["end_time"] = time.time()
        self._write("meta.json", meta)


class _NoopModule:
    """Swallows any attribute/call chain — handed to non-rank-zero
    Train workers by the gated integrations (reference: _NoopModule in
    air/integrations/mlflow.py) so logging isn't duplicated across a
    worker gang."""

    def __getattr__(self, name):
        return self

    def __call__(self, *a, **kw):
        return self


class _NoopRun:
    """Returned to non-rank-zero Train workers: logging must not be
    duplicated across a worker gang (reference: rank_zero_only)."""

    dir = None
    run_id = None

    def log_params(self, params) -> None:
        pass

    def log_metrics(self, metrics, step=None) -> None:
        pass

    def set_tags(self, tags) -> None:
        pass

    def finish(self, status: str = "FINISHED") -> None:
        pass


def _train_world_rank() -> Optional[int]:
    """Rank inside a Train worker gang, or None outside one."""
    try:
        from ray_tpu.train._internal.session import get_context

        ctx = get_context()
        if ctx is None:
            return None
        return ctx.get_world_rank()
    except Exception:
        return None


def setup_tracking(config: Optional[Dict[str, Any]] = None,
                   *,
                   experiment_name: str = "default",
                   run_name: Optional[str] = None,
                   run_id: Optional[str] = None,
                   tracking_root: Optional[str] = None,
                   tags: Optional[Dict[str, Any]] = None,
                   rank_zero_only: bool = True):
    """Open (or resume) a tracked run from inside a training function.

    Mirrors the reference's ``setup_mlflow`` contract
    (air/integrations/mlflow.py:32): the ``config`` dict is logged as
    run params; under Ray Train only the rank-zero worker gets a real
    run (others receive a no-op) unless ``rank_zero_only=False``.
    Passing the same ``run_id`` resumes (appends to) an existing run —
    the restore path after trial preemption.
    """
    if rank_zero_only:
        rank = _train_world_rank()
        if rank is not None and rank != 0:
            return _NoopRun()
    resumed = run_id is not None
    rid = run_id or uuid.uuid4().hex[:10]
    run = Run(_root(tracking_root), experiment_name, rid,
              run_name or rid, resumed=resumed)
    if tags:
        run.set_tags(tags)
    if config:
        run.log_params(config)
    return run


class TrackingLoggerCallback(LoggerCallback):
    """Tune callback: one local tracked run per trial.

    Params come from ``trial.config`` at start; every reported result
    appends a metrics line; completion stamps the final status.
    """

    def __init__(self, experiment_name: str = "default",
                 tracking_root: Optional[str] = None,
                 tags: Optional[Dict[str, Any]] = None):
        super().__init__()
        self._experiment = experiment_name
        self._tracking_root = tracking_root
        self._tags = dict(tags or {})
        self._runs: Dict[str, Run] = {}

    def _run_for(self, trial) -> Run:
        run = self._runs.get(trial.trial_id)
        if run is None:
            run = Run(_root(self._tracking_root), self._experiment,
                      trial.trial_id, f"trial_{trial.trial_id}",
                      resumed=True)
            meta = run._read("meta.json")
            if meta.get("status") != "RUNNING":
                meta.update({"status": "RUNNING",
                             "run_id": trial.trial_id,
                             "run_name": f"trial_{trial.trial_id}",
                             "experiment": self._experiment})
                meta.setdefault("start_time", time.time())
                run._write("meta.json", meta)
            if self._tags:
                run.set_tags(self._tags)
            self._runs[trial.trial_id] = run
        return run

    def on_trial_start(self, trial) -> None:
        self._run_for(trial).log_params(trial.config)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        run = self._run_for(trial)
        step = result.get("training_iteration")
        run.log_metrics(result, step=step)

    def on_trial_complete(self, trial) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish("ERRORED" if trial.error else "FINISHED")

    def on_experiment_end(self, trials: List) -> None:
        for run in self._runs.values():
            run.finish()
        self._runs.clear()


# ---------------------------------------------------------------- read side
def list_runs(tracking_root: Optional[str] = None,
              experiment: Optional[str] = None) -> List[Dict[str, Any]]:
    """All runs (newest first): meta + params + last metrics line."""
    root = _root(tracking_root)
    out: List[Dict[str, Any]] = []
    if not os.path.isdir(root):
        return out
    exps = [experiment] if experiment else sorted(os.listdir(root))
    for exp in exps:
        exp_dir = os.path.join(root, exp)
        if not os.path.isdir(exp_dir):
            continue
        for rid in sorted(os.listdir(exp_dir)):
            rdir = os.path.join(exp_dir, rid)
            if not os.path.isdir(rdir):
                continue
            entry: Dict[str, Any] = {"experiment": exp, "run_id": rid}
            try:
                with open(os.path.join(rdir, "meta.json")) as f:
                    entry.update(json.load(f))
            except (OSError, ValueError):
                entry["status"] = "UNKNOWN"
            try:
                with open(os.path.join(rdir, "params.json")) as f:
                    entry["params"] = json.load(f)
            except (OSError, ValueError):
                entry["params"] = {}
            last = None
            n = 0
            try:
                with open(os.path.join(rdir, "metrics.jsonl")) as f:
                    for line in f:
                        if line.strip():
                            last = line
                            n += 1
            except OSError:
                pass
            entry["num_metric_rows"] = n
            entry["last_metrics"] = json.loads(last) if last else {}
            out.append(entry)
    out.sort(key=lambda e: e.get("start_time") or 0, reverse=True)
    return out


def format_runs(runs: List[Dict[str, Any]]) -> str:
    """CLI rendering for ``ray_tpu runs``."""
    if not runs:
        return "no tracked runs"
    lines = [f"{'EXPERIMENT':<16} {'RUN':<12} {'STATUS':<9} "
             f"{'ROWS':>5}  LAST_METRICS"]
    for r in runs:
        last = {k: v for k, v in r["last_metrics"].items()
                if k not in ("ts",) and isinstance(v, (int, float))}
        brief = ", ".join(f"{k}={v:.4g}" if isinstance(v, float)
                          else f"{k}={v}"
                          for k, v in list(last.items())[:4])
        lines.append(f"{r['experiment']:<16.16} {r['run_id']:<12.12} "
                     f"{r.get('status', '?'):<9.9} "
                     f"{r['num_metric_rows']:>5}  {brief}")
    return "\n".join(lines)
