"""Experiment-tracking integrations.

Reference: python/ray/air/integrations/ (mlflow.py, wandb.py, comet.py).
The in-tree default is the dependency-free local tracker
(``tracking.py``); the mlflow/wandb adapters are gated on their
packages, same pattern as the Tune searcher matrix.
"""

from ray_tpu.air.integrations.tracking import (TrackingLoggerCallback,
                                               list_runs, setup_tracking)

__all__ = [
    "TrackingLoggerCallback",
    "setup_tracking",
    "list_runs",
]
