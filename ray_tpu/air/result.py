"""Result of a training/tuning run (reference python/ray/air/result.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Any] = None          # ray_tpu.train.Checkpoint
    error: Optional[BaseException] = None
    path: Optional[str] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: Optional[List[Tuple[Any, Dict[str, Any]]]] = None

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return self.metrics.get("config") if self.metrics else None
