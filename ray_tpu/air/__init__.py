"""ray_tpu.air — shared config/result types for the AI libraries.

Parity with python/ray/air/config.py and result.py in the reference.
"""

from ray_tpu.air.config import (
    ScalingConfig,
    RunConfig,
    FailureConfig,
    CheckpointConfig,
)
from ray_tpu.air.result import Result

__all__ = [
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "Result",
]
