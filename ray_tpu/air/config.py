"""Run/scaling/failure/checkpoint configs.

Reference: python/ray/air/config.py (`ScalingConfig` :170, `RunConfig`,
`FailureConfig`, `CheckpointConfig`). TPU-first addition: `topology` — a
pod-slice spec that makes the trainer lease whole slices atomically via
`slice_placement_group` instead of independent per-worker bundles.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    num_workers: worker actors (for TPU, one per host).
    use_tpu: workers request TPU chips and the gang is slice-atomic.
    chips_per_worker: TPU chips per host (v5e host = 4 or 8).
    topology: optional slice topology string (e.g. "v5e-64"); when set,
        placement is slice-atomic gang scheduling.
    resources_per_worker: extra custom resources per worker bundle.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 4
    topology: Optional[str] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    @property
    def total_chips(self) -> int:
        return self.num_workers * self.chips_per_worker if self.use_tpu else 0

    def bundle(self) -> Dict[str, float]:
        b: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            b["TPU"] = float(self.chips_per_worker)
        if self.resources_per_worker:
            b.update(self.resources_per_worker)
        return b


@dataclasses.dataclass
class FailureConfig:
    """max_failures: trial restarts from the latest checkpoint; -1 = inf."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """Top-K retention by a result metric (reference CheckpointConfig)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Dict[str, Any]] = None  # e.g. {"training_iteration": 10}
    verbose: int = 1
    # Trial loggers / lifecycle hooks (reference: RunConfig.callbacks;
    # None -> the default JSON+CSV loggers, [] -> none).
    callbacks: Optional[list] = None

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
