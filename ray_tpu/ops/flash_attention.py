"""Pallas TPU flash attention (forward AND backward kernels).

Online-softmax tiling keeps the working set in VMEM and the score matmuls
on the MXU; the kv-block grid axis iterates fastest so the (m, l, acc)
scratch accumulators persist across kv blocks for a fixed q block.
Backward is flash-style recompute in Pallas under `jax.custom_vjp`:
_bwd_dq_kernel (kv innermost) and _bwd_dkv_kernel (q innermost) re-derive
p from the saved row logsumexp.

Semantics match `ray_tpu.ops.attention.mha_reference` exactly, including
the kv-prefix causal offset when Sq != Sk (decode) and GQA. Sequence
lengths that don't divide the block size are zero-padded; padded kv
columns are masked by global index, padded q rows are sliced off.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _attn_mask(i, j, block_q, block_k, q_offset, sk_orig, causal):
    """Single source of truth for the fwd AND bwd score mask (they must
    agree exactly or the backward's recomputed softmax diverges)."""
    qi = q_offset + i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    ki = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = ki < sk_orig  # zero-padded kv columns
    if causal:
        mask = mask & (qi >= ki)
    return mask


def _block_contributes(i, j, block_q, block_k, q_offset, causal):
    """Causal block skip: kv block j contributes iff its first kv index
    <= the global position of q block i's last row."""
    if not causal:
        return True
    return j * block_k <= q_offset + i * block_q + block_q - 1


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                q_offset: int, sk_orig: int):
    """q_offset = sk_orig - sq_orig (kv-prefix shift for decode);
    sk_orig masks zero-padded kv columns."""
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (fastest)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    should_compute = _block_contributes(i, j, block_q, block_k, q_offset,
                                        causal)

    @pl.when(should_compute)
    def _body():
        # Matmul inputs keep their storage dtype: bf16 activations hit
        # the MXU's native bf16xbf16->f32 path (upcasting to f32 first
        # would force multi-pass f32 matmuls at a fraction of peak);
        # softmax statistics stay f32 via preferred_element_type.
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bk, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        mask = _attn_mask(i, j, block_q, block_k, q_offset, sk_orig,
                          causal)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        # l == 0 for zero-padded q rows (sliced off by the caller).
        # m == -inf marks FULLY-MASKED rows (decode with Sq > Sk): they
        # attend to nothing and must output exactly zero — without this,
        # p = exp(-inf - -inf) = 1 leaks uniform weights into acc.
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        row_live = m_ref[:] > _NEG_INF / 2
        o_ref[0, 0] = jnp.where(row_live, acc_ref[:] / l,
                                0.0).astype(o_ref.dtype)


def _fwd_kernel_lse(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                    l_ref, **kw):
    """Forward that also writes the row logsumexp (for the Pallas
    backward): lse = m + log(l)."""
    _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, **kw)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == nk - 1)
    def _write_lse():
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        lse_ref[0, 0] = m_ref[:] + jnp.log(l)  # [bq, 1]


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               with_lse=False):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    g = h // hkv
    block_q = min(block_q, max(sq, 1))
    block_k = min(block_k, max(sk, 1))
    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), _pad_seq(v,
                                                                      block_k)
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    grid = (b, h, sq_p // block_q, sk_p // block_k)

    kernel_fn = _fwd_kernel_lse if with_lse else _fwd_kernel
    kernel = functools.partial(
        kernel_fn, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
        q_offset=sk - sq, sk_orig=sk)
    out_specs = pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, i, j: (b_, h_, i, 0))
    out_shape = jax.ShapeDtypeStruct(qp.shape, q.dtype)
    if with_lse:
        # [B,H,Sq,1] keeps the last-two block dims TPU-tileable
        # ((block_q, 1) with 1 == full trailing dim).
        out_specs = [out_specs,
                     pl.BlockSpec((1, 1, block_q, 1),
                                  lambda b_, h_, i, j: (b_, h_, i, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((b, h, sq_p, 1), jnp.float32)]
    result = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j, g=g: (b_, h_ // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j, g=g: (b_, h_ // g, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    if with_lse:
        out, lse = result
        out = out[:, :, :sq] if sq_p != sq else out
        lse = lse[:, :, :sq] if sq_p != sq else lse
        return out, lse
    out = result
    return out[:, :, :sq] if sq_p != sq else out


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, sm_scale, causal, block_q,
                   block_k, q_offset, sk_orig):
    """dq for one q block, accumulated over kv blocks (innermost axis).
    ds = p * (dO v^T - delta) * scale; dq += ds k."""
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    should = _block_contributes(i, j, block_q, block_k, q_offset, causal)

    @pl.when(should)
    def _body():
        # Storage-dtype matmul inputs (native bf16 MXU path; f32 stats).
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                     # [bq, 1]
        delta = delta_ref[0, 0]                 # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = _attn_mask(i, j, block_q, block_k, q_offset, sk_orig,
                          causal)
        s = jnp.where(mask, s, _NEG_INF)
        # Fully-masked rows (decode with Sq > Sk, or padded rows) have
        # lse ~ -inf: their softmax is empty, p must be 0 — not
        # exp(-inf - -inf).
        p = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _fin():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                    block_q, block_k, q_offset, sk_orig):
    """dk/dv for one kv block (per q head — GQA groups reduced outside),
    accumulated over q blocks (innermost axis)."""
    j = pl.program_id(2)  # kv block
    i = pl.program_id(3)  # q block (innermost)
    nq = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    should = _block_contributes(i, j, block_q, block_k, q_offset, causal)

    @pl.when(should)
    def _body():
        # Storage-dtype matmul inputs (native bf16 MXU path; f32 stats).
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                     # [bq, 1]
        delta = delta_ref[0, 0]                 # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = _attn_mask(i, j, block_q, block_k, q_offset, sk_orig,
                          causal)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.where(lse <= _NEG_INF / 2, 0.0,
                      jnp.exp(s - lse))         # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale        # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _fin():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, sm_scale, causal, block_q, block_k,
               interpret, delta=None, grad_dtype=None):
    """grad_dtype overrides the dq/dk/dv output dtype (ring attention
    accumulates per-shard partials in f32); delta may be precomputed by
    callers that invoke this once per kv shard."""
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    grp = h // hkv
    block_q = min(block_q, max(sq, 1))
    block_k = min(block_k, max(sk, 1))
    dq_dtype = grad_dtype or q.dtype
    dk_dtype = grad_dtype or k.dtype
    dv_dtype = grad_dtype or v.dtype

    if delta is None:
        # delta = rowsum(dO * O) — cheap, fused by XLA. [B,H,Sq,1] layout
        # keeps the Pallas row blocks TPU-tileable.
        delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)  # [B,H,Sq,1]

    qp = _pad_seq(q, block_q)
    gp = _pad_seq(g, block_q)
    kp, vp = _pad_seq(k, block_k), _pad_seq(v, block_k)
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    pad_q = sq_p - sq
    if pad_q:
        # Padded q rows get lse=0 and delta=0. Their p is NOT zero (for
        # unmasked columns p = exp(s-0)), but every contribution is
        # multiplied by do=0 (gp zero-padded) and delta=0, so dk/dv/dq
        # stay exact — do not stop zero-padding gp.
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q), (0, 0)))

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, q_offset=sk - sq, sk_orig=sk)

    # --- dq: grid (b, h, nq, nk), kv innermost (axis2=q, axis3=kv) ---
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b, h, sq_p // block_q, sk_p // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j, g_=grp:
                         (b_, h_ // g_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j, g_=grp:
                         (b_, h_ // g_, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qp.shape, dq_dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, gp, lse, delta)

    # --- dk/dv: grid (b, h, nk, nq), q innermost (axis2=kv, axis3=q);
    # per-q-head then group reduce (GQA) ---
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b, h, sk_p // block_k, sq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i, g_=grp:
                         (b_, h_ // g_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i, g_=grp:
                         (b_, h_ // g_, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, j, i: (b_, h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk_p, d), dk_dtype),
            jax.ShapeDtypeStruct((b, h, sk_p, d), dv_dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, gp, lse, delta)

    dq = dq[:, :, :sq] if sq_p != sq else dq
    dk_h = dk_h[:, :, :sk] if sk_p != sk else dk_h
    dv_h = dv_h[:, :, :sk] if sk_p != sk else dv_h
    if grp > 1:
        dk = dk_h.reshape(b, hkv, grp, sk, d).sum(axis=2).astype(dk_dtype)
        dv = dv_h.reshape(b, hkv, grp, sk, d).sum(axis=2).astype(dv_dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret,
                   residuals, g):
    q, k, v, out, lse = residuals
    return _flash_bwd(q, k, v, out, lse, g, sm_scale, causal, block_q,
                      block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jax.Array:
    """q: [B,H,Sq,D]; k,v: [B,Hkv,Sk,D] (GQA when Hkv < H). -> [B,H,Sq,D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, float(sm_scale), bool(causal),
                  int(block_q), int(block_k), bool(interpret))
