"""Pallas TPU flash attention (forward kernel + recompute backward).

Online-softmax tiling keeps the working set in VMEM and the score matmuls
on the MXU; the kv-block grid axis iterates fastest so the (m, l, acc)
scratch accumulators persist across kv blocks for a fixed q block.
Backward is flash-style recompute in plain JAX under `jax.custom_vjp`
(XLA fuses it well; a Pallas backward is a later optimization).

Semantics match `ray_tpu.ops.attention.mha_reference` exactly, including
the kv-prefix causal offset when Sq != Sk (decode) and GQA. Sequence
lengths that don't divide the block size are zero-padded; padded kv
columns are masked by global index, padded q rows are sliced off.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                q_offset: int, sk_orig: int):
    """q_offset = sk_orig - sq_orig (kv-prefix shift for decode);
    sk_orig masks zero-padded kv columns."""
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (fastest)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: kv block j contributes iff its first kv index <= the global
    # position of this q block's last row.
    should_compute = True
    if causal:
        should_compute = (j * block_k
                          <= q_offset + i * block_q + block_q - 1)

    @pl.when(should_compute)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        qi = q_offset + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        ki = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = ki < sk_orig  # zero-padded kv columns
        if causal:
            mask = mask & (qi >= ki)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        # l == 0 only for zero-padded q rows (sliced off by the caller).
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    g = h // hkv
    block_q = min(block_q, max(sq, 1))
    block_k = min(block_k, max(sk, 1))
    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), _pad_seq(v,
                                                                      block_k)
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    grid = (b, h, sq_p // block_q, sk_p // block_k)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
        q_offset=sk - sq, sk_orig=sk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j, g=g: (b_, h_ // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j, g=g: (b_, h_ // g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :sq] if sq_p != sq else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, interpret,
                   residuals, g):
    from ray_tpu.ops.attention import mha_reference

    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: mha_reference(
            q_, k_, v_, causal=causal, sm_scale=sm_scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jax.Array:
    """q: [B,H,Sq,D]; k,v: [B,Hkv,Sk,D] (GQA when Hkv < H). -> [B,H,Sq,D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, float(sm_scale), bool(causal),
                  int(block_q), int(block_k), bool(interpret))
