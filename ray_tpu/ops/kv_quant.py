"""Low-bit paged-KV quantization: per-block, per-kv-head scales.

The paged engine stores its KV pool as ``[L, NB, T, KV, D]`` blocks; to
double the concurrent requests per HBM byte the pool can instead hold
int8 (qmax 127) or fp8-e4m3 (qmax 448) values plus a parallel f32 scale
slab shaped ``[L, NB, KV]`` — one scale per block per kv head, indexed
by the SAME physical block ids as the pages so the refcounted BlockPool
ledger covers both with no extra alloc/free sites.

Quantization is symmetric absmax: ``s = amax / qmax`` over a block's
valid slots (``s = 1.0`` for all-zero blocks so dequant stays exact and
finite), ``q = round_or_cast(clip(x / s, -qmax, qmax))``, dequant
``x' = q.astype(f32) * s``.  Two properties the engine leans on:

* **Requantization is byte-stable.** Re-quantizing a dequantized block
  with a freshly recomputed scale reproduces the identical bytes: the
  recomputed ``amax' = max|q|*s`` differs from ``amax`` only by float
  rounding, so ``s'/s = 1 ± O(2^-23)`` and ``round(q * s/s')`` (int8) /
  nearest-fp8 rounding (e4m3, whose relative spacing is ≥ 2^-3) lands
  back on ``q`` exactly.  This is what keeps shared prefix blocks
  byte-identical under `_prefill_rows_paged`'s whole-view write-back —
  provided the dequantized view stays float32 end to end (a bf16
  round-trip would break it).
* **Stale slots are zeroed at every write.** A block's scale is an
  absmax over ALL its slots, so garbage left by a previous tenant (or a
  rejected speculative window) would silently coarsen the valid tokens'
  quantization.  Every write site therefore zeroes slots at/beyond the
  row's written frontier before recomputing the scale.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "KVQuantSpec",
    "KV_QUANT_MODES",
    "resolve_kv_quant",
    "block_scale",
    "quantize",
    "dequantize",
    "paged_quant_write",
]

KV_QUANT_MODES = ("int8", "fp8_e4m3")


@dataclasses.dataclass(frozen=True)
class KVQuantSpec:
    """Hashable description of one quantized-KV mode (safe to pass as a
    jit static argument: all fields are plain python scalars)."""

    name: str         # "int8" | "fp8_e4m3"
    dtype_name: str   # numpy dtype name of the stored pool values
    qmax: float       # largest representable magnitude pre-scale
    itemsize: int = 1  # bytes per stored value

    @property
    def dtype(self):
        return jnp.dtype(self.dtype_name)

    @property
    def is_int(self) -> bool:
        return self.name == "int8"


_SPECS = {
    "int8": KVQuantSpec("int8", "int8", 127.0, 1),
    "fp8_e4m3": KVQuantSpec("fp8_e4m3", "float8_e4m3fn", 448.0, 1),
}


def resolve_kv_quant(name: Optional[str]) -> Optional[KVQuantSpec]:
    """Map an engine-level ``kv_quant`` knob to a spec (None -> None)."""
    if name is None:
        return None
    spec = _SPECS.get(name)
    if spec is None:
        raise ValueError(
            f"kv_quant must be one of {KV_QUANT_MODES} or None, got "
            f"{name!r}")
    return spec


def block_scale(amax: jax.Array, qspec: KVQuantSpec) -> jax.Array:
    """amax -> scale with the all-zero guard (scale 1.0 so dequant of a
    zero block is exactly zero and never divides by zero)."""
    return jnp.where(amax > 0, amax / qspec.qmax, 1.0).astype(jnp.float32)


def quantize(x: jax.Array, scale: jax.Array,
             qspec: KVQuantSpec) -> jax.Array:
    """``x`` f32 -> stored dtype; ``scale`` must broadcast against x."""
    y = jnp.clip(x.astype(jnp.float32) / scale, -qspec.qmax, qspec.qmax)
    if qspec.is_int:
        y = jnp.round(y)
    return y.astype(qspec.dtype)


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Stored dtype -> f32.  Keep the result f32 (see module docstring:
    a bf16 round-trip breaks requantization byte-stability)."""
    return q.astype(jnp.float32) * scale


def paged_quant_write(pages: jax.Array, scales: jax.Array, bt: jax.Array,
                      start: jax.Array, vals: jax.Array,
                      qspec: KVQuantSpec
                      ) -> Tuple[jax.Array, jax.Array]:
    """Read-modify-write ``vals`` [B, S, KV, D] into quantized ``pages``
    [NB, T, KV, D] at contiguous cache slots ``start[b] + s`` routed
    through block table ``bt`` [B, MB], recomputing the per-block
    per-kv-head ``scales`` [NB, KV] of every touched block.

    This is the decode/spec write site: S == 1 for plain decode, S ==
    the draft/verify window for speculation.  The window can straddle
    block boundaries, so the write is a static loop over the (at most
    ``(S + T - 2)//T + 1``) window blocks; each iteration RMWs ONE block
    per row — gather + dequant, scatter this window's tokens that land
    in that block (offset T + ``mode="drop"`` masks the rest), zero
    every slot at/beyond ``start + S`` (stale garbage from a previous
    tenant or a rejected speculative window must not leak into the
    absmax), requantize with the fresh scale, scatter back.

    Rows whose window block index runs off the table (retired rows, or
    frontiers at max_len) resolve to physical block 0 — the reserved
    null block, never attended — exactly like the unquantized write
    path's masked scatter.
    """
    B, S, KV, D = vals.shape
    T = pages.shape[1]
    MB = bt.shape[1]
    vals = vals.astype(jnp.float32)
    bidx = jnp.arange(B)
    nbw = (S + T - 2) // T + 1            # max blocks a window can touch
    off0 = start % T                      # [B] offset in first block
    for w in range(nbw):
        lb = start // T + w               # [B] logical block index
        blk = jnp.where(lb < MB, bt[bidx, jnp.minimum(lb, MB - 1)], 0)
        cur = dequantize(pages[blk], scales[blk][:, None, :, None])
        # token s sits at window position off0 + s; it lands in this
        # iteration's block iff (off0 + s) // T == w.  Offset T is OOB
        # and dropped.
        pos = off0[:, None] + jnp.arange(S)[None, :]          # [B, S]
        offs = jnp.where(pos // T == w, pos % T, T)
        cur = cur.at[bidx[:, None], offs].set(vals, mode="drop")
        # zero stale slots at/beyond the written frontier
        slot = (lb * T)[:, None] + jnp.arange(T)[None, :]     # [B, T]
        live = slot < (start + S)[:, None]
        cur = jnp.where(live[:, :, None, None], cur, 0.0)
        amax = jnp.max(jnp.abs(cur), axis=(1, 3))             # [B, KV]
        s_new = block_scale(amax, qspec)
        pages = pages.at[blk].set(quantize(
            cur, s_new[:, None, :, None], qspec))
        scales = scales.at[blk].set(s_new)
    return pages, scales
