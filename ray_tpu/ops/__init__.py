"""ray_tpu.ops — TPU compute kernels (Pallas) with pure-JAX fallbacks.

The reference has no kernel layer (it delegates compute to torch/CUDA);
this package is where the new framework's performance lives: flash
attention on the MXU, ring attention over the ICI `sp` axis for long
context (capability absent from the reference — SURVEY.md §5.7).
"""

from ray_tpu.ops.attention import attention, mha_reference
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.ulysses import ulysses_attention

__all__ = [
    "attention",
    "mha_reference",
    "flash_attention",
    "ring_attention",
    "ulysses_attention",
]
