"""Ring attention — sequence/context parallelism over an ICI mesh axis.

Capability absent from the reference (verified in SURVEY.md §5.7): long
sequences are sharded over the `sp` mesh axis; each device computes
blockwise attention for its local q shard while k/v shards rotate around
the ring via `ppermute`, overlapping compute with ICI transfer. Online
softmax combines partial results exactly (same math as flash attention).

`ring_attention` is SPMD-internal: call it inside `shard_map`/pjit with
q,k,v already sharded over `axis_name` on the sequence dim.
`ring_attention_sharded` wraps it for a given mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, m, l, acc, q_off, k_off, causal, sm_scale):
    """One online-softmax accumulation step. q:[B,H,Sq,D] k,v:[B,Hkv,Sk,D]."""
    from ray_tpu.ops.attention import _repeat_kv

    h, hkv = q.shape[1], k.shape[1]
    k = _repeat_kv(k, h // hkv)
    v = _repeat_kv(v, h // hkv)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        qi = q_off + jnp.arange(sq)[:, None]
        ki = k_off + jnp.arange(sk)[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # Guard fully-masked steps: exp(-inf - -inf) -> keep alpha finite.
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   *,
                   axis_name: str = "sp",
                   causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention. Shapes are LOCAL: q [B,H,S/sp,D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq_local, d = q.shape
    sk_local = k.shape[2]
    q_off = my_idx * sq_local

    m0 = jnp.full((b, h, sq_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sq_local, d), jnp.float32)

    # Ring: device i sends its current kv to i+1; after t steps device i
    # holds the shard originally on (i - t) mod axis_size.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, t):
        k_cur, v_cur, m, l, acc = carry
        src = jax.lax.rem(my_idx - t + axis_size, axis_size)
        k_off = src * sk_local
        if causal:
            # Skip shards entirely in the future of this q shard.
            relevant = k_off <= q_off + sq_local - 1
            m, l, acc = jax.lax.cond(
                relevant,
                lambda: _block_attn(q, k_cur, v_cur, m, l, acc,
                                    q_off, k_off, True, sm_scale),
                lambda: (m, l, acc))
        else:
            m, l, acc = _block_attn(q, k_cur, v_cur, m, l, acc,
                                    q_off, k_off, False, sm_scale)
        # Skip the rotation on the last step: its output is never consumed,
        # and the dead ppermute would cost one full kv shard of ICI traffic.
        k_nxt, v_nxt = jax.lax.cond(
            t < axis_size - 1,
            lambda: (jax.lax.ppermute(k_cur, axis_name, perm),
                     jax.lax.ppermute(v_cur, axis_name, perm)),
            lambda: (k_cur, v_cur))
        return (k_nxt, v_nxt, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(axis_size))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (non-causal edge)
    return (acc / l).astype(q.dtype)


def ring_attention_sharded(q: jax.Array,
                           k: jax.Array,
                           v: jax.Array,
                           mesh: Mesh,
                           *,
                           axis_name: str = "sp",
                           causal: bool = True,
                           sm_scale: Optional[float] = None) -> jax.Array:
    """shard_map wrapper: q,k,v are GLOBAL [B,H,S,D], sharded over seq."""
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)(q, k, v)
