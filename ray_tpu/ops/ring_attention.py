"""Ring attention — sequence/context parallelism over an ICI mesh axis.

Capability absent from the reference (verified in SURVEY.md §5.7): long
sequences are sharded over the `sp` mesh axis; each device computes
blockwise attention for its local q shard while k/v shards rotate around
the ring via `ppermute`, overlapping compute with ICI transfer. Online
softmax combines partial results exactly (same math as flash attention).

Two implementations:
- impl="pallas" (default): each ring step runs the Pallas flash kernel
  (ops/flash_attention.py) on (q_local, kv_shard) — MXU matmuls, VMEM
  tiling — and the per-shard (o, lse) pairs combine exactly in f32.
  Because ring shards are equal-sized, every step is statically either
  fully-past (causal=False kernel), diagonal (standard causal kernel),
  or causally skipped — no dynamic-offset kernel variant needed. The
  backward is a second ring pass over the Pallas backward kernels with
  grad accumulators rotating alongside the kv shards.
- impl="xla": the original einsum online-softmax scan (fallback/debug).

`ring_attention` is SPMD-internal: call it inside `shard_map`/pjit with
q,k,v already sharded over `axis_name` on the sequence dim.
`ring_attention_sharded` wraps it for a given mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.flash_attention import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q,
                                         _flash_bwd, _flash_fwd)

_NEG_INF = -1e30


def _block_attn(q, k, v, m, l, acc, q_off, k_off, causal, sm_scale):
    """One online-softmax accumulation step. q:[B,H,Sq,D] k,v:[B,Hkv,Sk,D]."""
    from ray_tpu.ops.attention import _repeat_kv

    h, hkv = q.shape[1], k.shape[1]
    k = _repeat_kv(k, h // hkv)
    v = _repeat_kv(v, h // hkv)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        qi = q_off + jnp.arange(sq)[:, None]
        ki = k_off + jnp.arange(sk)[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # Guard fully-masked steps: exp(-inf - -inf) -> keep alpha finite.
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _combine(o, lse, o_i, lse_i):
    """Exact combination of two normalized flash partials (f32).

    o = acc/l with lse = m + log(l); the merged output is
    (acc0 + acc1) / (l0 + l1) computed in the max-lse frame."""
    m = jnp.maximum(lse, lse_i)
    w0 = jnp.exp(lse - m)
    w1 = jnp.exp(lse_i - m)
    denom = w0 + w1
    o_c = (o * w0 + o_i.astype(jnp.float32) * w1) / denom
    return o_c, m + jnp.log(denom)


def _ring_pallas_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_q,
                          block_k, interpret):
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq_local, d = q.shape
    if causal and k.shape[2] != sq_local:
        raise ValueError(
            "causal ring attention requires equal q/kv shards "
            f"(got Sq={sq_local}, Sk={k.shape[2]})")
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    o0 = jnp.zeros((b, h, sq_local, d), jnp.float32)
    lse0 = jnp.full((b, h, sq_local, 1), _NEG_INF, jnp.float32)

    def chunk(k_cur, v_cur, src):
        """Flash kernel on one kv shard: statically causal=False for
        fully-past shards, the standard causal kernel on the diagonal."""
        def past():
            return _flash_fwd(q, k_cur, v_cur, sm_scale, False, block_q,
                              block_k, interpret, with_lse=True)

        if not causal:
            return past()

        def diag():
            return _flash_fwd(q, k_cur, v_cur, sm_scale, True, block_q,
                              block_k, interpret, with_lse=True)

        return jax.lax.cond(src == my_idx, diag, past)

    def step(carry, t):
        k_cur, v_cur, o, lse = carry
        src = jax.lax.rem(my_idx - t + axis_size, axis_size)

        def compute():
            o_i, lse_i = chunk(k_cur, v_cur, src)
            return _combine(o, lse, o_i, lse_i)

        if causal:
            o, lse = jax.lax.cond(src <= my_idx, compute,
                                  lambda: (o, lse))
        else:
            o, lse = compute()
        k_nxt, v_nxt = jax.lax.cond(
            t < axis_size - 1,
            lambda: (jax.lax.ppermute(k_cur, axis_name, perm),
                     jax.lax.ppermute(v_cur, axis_name, perm)),
            lambda: (k_cur, v_cur))
        return (k_nxt, v_nxt, o, lse), None

    (_, _, o, lse), _ = jax.lax.scan(
        step, (k, v, o0, lse0), jnp.arange(axis_size))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_pallas(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                 interpret):
    out, _ = _ring_pallas_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                   block_q, block_k, interpret)
    return out


def _ring_pallas_vjp_fwd(q, k, v, axis_name, causal, sm_scale, block_q,
                         block_k, interpret):
    out, lse = _ring_pallas_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                     block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_pallas_vjp_bwd(axis_name, causal, sm_scale, block_q, block_k,
                         interpret, residuals, g):
    """Second ring pass: kv shards rotate together with their (dk, dv)
    accumulators; each device adds its local contribution via the Pallas
    backward kernels, then one final rotation delivers each accumulator
    to its home shard."""
    q, k, v, out, lse = residuals
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    # Loop-invariant across ring steps: hoist out of the scan. grad_dtype
    # f32 keeps per-shard partials unquantized until the final cast.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def chunk_bwd(k_cur, v_cur, src):
        def past():
            return _flash_bwd(q, k_cur, v_cur, out, lse, g, sm_scale,
                              False, block_q, block_k, interpret,
                              delta=delta, grad_dtype=jnp.float32)

        if not causal:
            return past()

        def diag():
            return _flash_bwd(q, k_cur, v_cur, out, lse, g, sm_scale,
                              True, block_q, block_k, interpret,
                              delta=delta, grad_dtype=jnp.float32)

        return jax.lax.cond(src == my_idx, diag, past)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)

    def step(carry, t):
        k_cur, v_cur, dk_acc, dv_acc, dq = carry
        src = jax.lax.rem(my_idx - t + axis_size, axis_size)

        def compute():
            dq_i, dk_i, dv_i = chunk_bwd(k_cur, v_cur, src)
            return (dq + dq_i, dk_acc + dk_i, dv_acc + dv_i)

        if causal:
            dq, dk_acc, dv_acc = jax.lax.cond(
                src <= my_idx, compute, lambda: (dq, dk_acc, dv_acc))
        else:
            dq, dk_acc, dv_acc = compute()
        k_nxt, v_nxt, dk_nxt, dv_nxt = jax.lax.cond(
            t < axis_size - 1,
            lambda: tuple(jax.lax.ppermute(x, axis_name, perm)
                          for x in (k_cur, v_cur, dk_acc, dv_acc)),
            lambda: (k_cur, v_cur, dk_acc, dv_acc))
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq), None

    (_, _, dk_acc, dv_acc, dq), _ = jax.lax.scan(
        step, (k, v, dk0, dv0, dq0), jnp.arange(axis_size))
    # After size-1 rotations, device d holds shard (d+1)%size's
    # accumulator; one more forward rotation brings each home.
    dk = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_pallas.defvjp(_ring_pallas_vjp_fwd, _ring_pallas_vjp_bwd)


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   *,
                   axis_name: str = "sp",
                   causal: bool = True,
                   sm_scale: Optional[float] = None,
                   impl: str = "auto",
                   block_q: int = DEFAULT_BLOCK_Q,
                   block_k: int = DEFAULT_BLOCK_K,
                   interpret: Optional[bool] = None) -> jax.Array:
    """Per-shard ring attention. Shapes are LOCAL: q [B,H,S/sp,D].

    impl="auto" picks the Pallas kernel on TPU and the XLA einsum scan
    elsewhere (Pallas off-TPU would run in interpret emulation — correct
    but far slower than XLA). Pass impl explicitly to override.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return _ring_pallas(q, k, v, axis_name, bool(causal),
                            float(sm_scale), int(block_q), int(block_k),
                            bool(interpret))
    if impl != "xla":
        raise ValueError(f"unknown ring attention impl {impl!r}")
    return _ring_xla(q, k, v, axis_name=axis_name, causal=causal,
                     sm_scale=sm_scale)


def _ring_xla(q: jax.Array,
              k: jax.Array,
              v: jax.Array,
              *,
              axis_name: str = "sp",
              causal: bool = True,
              sm_scale: Optional[float] = None) -> jax.Array:
    """Plain-JAX einsum ring (differentiable via autodiff)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq_local, d = q.shape
    sk_local = k.shape[2]
    q_off = my_idx * sq_local

    m0 = jnp.full((b, h, sq_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sq_local, d), jnp.float32)

    # Ring: device i sends its current kv to i+1; after t steps device i
    # holds the shard originally on (i - t) mod axis_size.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, t):
        k_cur, v_cur, m, l, acc = carry
        src = jax.lax.rem(my_idx - t + axis_size, axis_size)
        k_off = src * sk_local
        if causal:
            # Skip shards entirely in the future of this q shard.
            relevant = k_off <= q_off + sq_local - 1
            m, l, acc = jax.lax.cond(
                relevant,
                lambda: _block_attn(q, k_cur, v_cur, m, l, acc,
                                    q_off, k_off, True, sm_scale),
                lambda: (m, l, acc))
        else:
            m, l, acc = _block_attn(q, k_cur, v_cur, m, l, acc,
                                    q_off, k_off, False, sm_scale)
        # Skip the rotation on the last step: its output is never consumed,
        # and the dead ppermute would cost one full kv shard of ICI traffic.
        k_nxt, v_nxt = jax.lax.cond(
            t < axis_size - 1,
            lambda: (jax.lax.ppermute(k_cur, axis_name, perm),
                     jax.lax.ppermute(v_cur, axis_name, perm)),
            lambda: (k_cur, v_cur))
        return (k_nxt, v_nxt, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(axis_size))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (non-causal edge)
    return (acc / l).astype(q.dtype)


def ring_attention_sharded(q: jax.Array,
                           k: jax.Array,
                           v: jax.Array,
                           mesh: Mesh,
                           *,
                           axis_name: str = "sp",
                           causal: bool = True,
                           sm_scale: Optional[float] = None,
                           impl: str = "auto",
                           block_q: int = DEFAULT_BLOCK_Q,
                           block_k: int = DEFAULT_BLOCK_K,
                           interpret: Optional[bool] = None) -> jax.Array:
    """shard_map wrapper: q,k,v are GLOBAL [B,H,S,D], sharded over seq."""
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale, impl=impl,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)(q, k, v)
