"""Fused block-walking paged decode-attention kernel (Pallas/Mosaic).

The pure-lax reference in `ops/attention.py:paged_attention` gathers a
per-row dense view ``[B, MB*T, KV, D]`` and lets XLA fuse it — correct,
but the gathered view is materialization pressure exactly proportional
to the block-table span. This kernel instead walks each row's block
table block-by-block in VMEM with a flash-style online-softmax inner
loop: the physical page for grid step ``j`` is resolved through a
scalar-prefetched block table inside the BlockSpec index map, so page
gather + (optional int8/fp8) dequantization + attend are fused and no
dense view ever exists.

Grid is ``(B, H, MB)`` with the block-walk axis innermost and marked
"arbitrary" (the online-softmax recurrence is sequential); scratch is
the usual flash trio — f32 accumulator ``[S, D]`` plus running max/sum
``[S, 1]`` — carried across the walk and finalized on the last block.
Masked positions follow the reference exactly: causal ``slot <=
q_slot`` plus the ``kv_valid_len`` cap, fully-masked rows produce 0.

Pallas cannot lower to this box's TPU toolchain, so the kernel is
validated in **interpret mode** against the pure-lax reference
(tests/test_engine_kv_quant.py sweeps (B, MB, T, KV, D) shapes incl.
GQA, ragged valid lengths and quantized pools) — the same oracle
pattern ops/flash_attention.py uses. On TPU `impl="auto"` routes here;
off-TPU it stays on the reference path and this kernel runs only when
asked for explicitly (then in interpret mode).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pragma: no cover - import guard for broken toolchains
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_ERR = None
except Exception as _e:  # noqa: BLE001
    pl = None
    pltpu = None
    _PALLAS_ERR = _e

_NEG_INF = -1e30

__all__ = ["paged_attention_kernel"]


def _kernel(bt_ref, lim_ref, q_ref, k_ref, v_ref, *rest, scale, n_blocks,
            seq_q, has_scale):
    """One (b, h, j) grid step: fold page j of row b into the online
    softmax. Scalar-prefetch refs: ``bt_ref`` [B, MB] block table (also
    consumed by the BlockSpec index maps), ``lim_ref`` [B, S+1] packing
    each query's cache slot plus the valid-length cap."""
    if has_scale:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0, :, 0, :].astype(jnp.float32)          # [S, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)          # [T, D]
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    if has_scale:
        k = k * ks_ref[0, 0]                           # dequant in VMEM
        v = v * vs_ref[0, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    t = k.shape[0]
    slot = j * t + jax.lax.broadcasted_iota(jnp.int32, (seq_q, t), 1)
    q_slots = jnp.stack([lim_ref[b, i] for i in range(seq_q)])
    valid_len = lim_ref[b, seq_q]
    mask = (slot <= q_slots[:, None]) & (slot < valid_len)
    s = jnp.where(mask, s, _NEG_INF)
    m_prev = m_ref[...]                                # [S, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # explicit zero (not just exp underflow): a fully-masked block with
    # m still at -inf would otherwise yield exp(0) == 1 per position
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = m_new

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l = l_ref[...]
        l = jnp.where(l == 0.0, 1.0, l)
        row_live = m_ref[...] > _NEG_INF / 2
        o_ref[0, :, 0, :] = jnp.where(
            row_live, acc_ref[...] / l, 0.0).astype(o_ref.dtype)


def paged_attention_kernel(q: jax.Array,
                           k_pages: jax.Array,
                           v_pages: jax.Array,
                           block_tables: jax.Array,
                           q_slots: jax.Array,
                           *,
                           kv_valid_len,
                           sm_scale: Optional[float] = None,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Same contract as `ops.attention.paged_attention` (reference
    impl), fused. ``interpret=None`` resolves to True off-TPU."""
    if pl is None:  # pragma: no cover
        raise NotImplementedError(
            f"paged_attention impl='flash' needs Pallas, which failed "
            f"to import in this environment: {_PALLAS_ERR!r}")
    B, S, H, D = q.shape
    NB, T, KV, _ = k_pages.shape
    MB = block_tables.shape[1]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    g = H // KV
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = sm_scale if sm_scale is not None else D ** -0.5
    has_scale = k_scale is not None

    bt = block_tables.astype(jnp.int32)
    lim = jnp.concatenate(
        [q_slots.astype(jnp.int32),
         jnp.full((B, 1), kv_valid_len, jnp.int32)], axis=1)   # [B, S+1]

    def page_map(b, h, j, bt_ref, lim_ref):
        return (bt_ref[b, j], 0, h // g, 0)

    def scale_map(b, h, j, bt_ref, lim_ref):
        return (bt_ref[b, j], h // g)

    in_specs = [
        pl.BlockSpec((1, S, 1, D), lambda b, h, j, bt_ref, lim_ref:
                     (b, 0, h, 0)),                    # q
        pl.BlockSpec((1, T, 1, D), page_map),          # k page
        pl.BlockSpec((1, T, 1, D), page_map),          # v page
    ]
    args = [q, k_pages, v_pages]
    if has_scale:
        in_specs += [pl.BlockSpec((1, 1), scale_map),
                     pl.BlockSpec((1, 1), scale_map)]
        args += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, S, 1, D), lambda b, h, j, bt_ref,
                               lim_ref: (b, 0, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((S, D), jnp.float32),
            pltpu.VMEM((S, 1), jnp.float32),
            pltpu.VMEM((S, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_kernel, scale=scale, n_blocks=MB,
                               seq_q=S, has_scale=has_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(bt, lim, *args)
