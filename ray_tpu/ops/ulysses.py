"""Ulysses (DeepSpeed-Ulysses-style) sequence parallelism.

Absent from the reference (SURVEY.md §5.7) — a new-framework capability.
Complement to ring attention (ops/ring_attention.py): instead of rotating
K/V blocks around the `sp` ring, Ulysses swaps the sharded dimension with
two all-to-alls over ICI — sequence-sharded activations become
head-sharded for the attention itself, so each device runs FULL-sequence
attention on H/sp heads (exact, no online-softmax bookkeeping; best when
n_heads % sp == 0 and sequence fits HBM after the swap).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "sp", causal: bool = True,
                      impl: str = "auto", block_q=None,
                      block_k=None) -> jax.Array:
    """q,k,v: [B, H, S_shard, D] (sequence sharded over axis_name, inside
    shard_map/jit). Returns [B, H, S_shard, D].

    all_to_all #1: split heads, gather sequence -> [B, H/sp, S, D]
    full attention on the local head group
    all_to_all #2: split sequence, gather heads -> [B, H, S_shard, D]
    """
    from ray_tpu.ops import attention

    sp = jax.lax.psum(1, axis_name)
    if q.shape[1] % sp:
        raise ValueError(
            f"n_heads={q.shape[1]} must be divisible by sp={sp}")

    def swap_in(x):  # [B,H,Ss,D] -> [B,H/sp,S,D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def swap_out(x):  # [B,H/sp,S,D] -> [B,H,Ss,D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = swap_in(q), swap_in(k), swap_in(v)
    out = attention(qh, kh, vh, causal=causal, impl=impl,
                    block_q=block_q, block_k=block_k)
    return swap_out(out)
