"""Attention dispatch + pure-JAX reference implementation.

`attention` picks the best implementation for the current backend:
Pallas flash attention on TPU, an XLA-fused reference elsewhere (CPU
tests run on the reference path; the Pallas kernel is also unit-tested in
interpret mode against it).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, Hkv, S, D] -> [B, Hkv*n_rep, S, D] for grouped-query attention."""
    if n_rep == 1:
        return k
    b, hkv, s, d = k.shape
    k = jnp.broadcast_to(k[:, :, None], (b, hkv, n_rep, s, d))
    return k.reshape(b, hkv * n_rep, s, d)


def mha_reference(q: jax.Array,
                  k: jax.Array,
                  v: jax.Array,
                  *,
                  causal: bool = True,
                  sm_scale: Optional[float] = None,
                  segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Stable-softmax attention. q: [B,H,Sq,D]; k,v: [B,Hkv,Sk,D].

    Computes in float32 regardless of input dtype (bf16 inputs hit the MXU
    via preferred_element_type), returns q.dtype.
    """
    *_, h, sq, d = q.shape
    hkv = k.shape[1]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    k = _repeat_kv(k, h // hkv)
    v = _repeat_kv(v, h // hkv)
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else d ** -0.5

    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        qi = jnp.arange(sq)[:, None] + (sk - sq)  # allow kv prefix (decode)
        ki = jnp.arange(sk)[None, :]
        mask = qi >= ki
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask[None, None] & seg)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        # A row with NO unmasked column attends to nothing: define its
        # output (and gradient) as zero, not softmax's accidental
        # uniform distribution over -inf logits. Matches the Pallas
        # kernels' semantics.
        row_live = mask.any(-1, keepdims=True)
        probs = jnp.where(row_live, probs, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def attention(q: jax.Array,
              k: jax.Array,
              v: jax.Array,
              *,
              causal: bool = True,
              sm_scale: Optional[float] = None,
              impl: str = "auto") -> jax.Array:
    """Dispatch: impl in {'auto', 'flash', 'reference'}."""
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "reference"
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
