"""Attention dispatch + pure-JAX reference implementation.

`attention` picks the best implementation for the current backend:
Pallas flash attention on TPU, an XLA-fused reference elsewhere (CPU
tests run on the reference path; the Pallas kernel is also unit-tested in
interpret mode against it).

SPMD: Mosaic kernels cannot be auto-partitioned by GSPMD, so under a
multi-device mesh the flash kernel is wrapped in a `shard_map` over the
batch/head axes (sequence stays whole per shard — sp uses the dedicated
ring/ulysses paths). The active mesh reaches this dispatch through a
trace-time context (`spmd_mesh_scope`) set by make_sharded_train_step.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_SPMD_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_spmd_mesh", default=None)


@contextlib.contextmanager
def spmd_mesh_scope(mesh):
    """Announce the mesh a jitted program is being traced for, so kernel
    dispatch can pick SPMD-safe forms. Trace-time only — no runtime
    effect."""
    token = _SPMD_MESH.set(mesh)
    try:
        yield
    finally:
        _SPMD_MESH.reset(token)


def _in_manual_region() -> bool:
    """True inside a shard_map body (axes already manual there)."""
    try:
        am = jax.sharding.get_abstract_mesh()
    except Exception:
        return False
    if am is None or not getattr(am, "shape", None):
        return False
    return any("Manual" in str(t) for t in getattr(am, "axis_types", ()))


def _flash_spmd_spec(q_shape, kv_shape, mesh):
    """PartitionSpec over (batch, heads) for a [B,H,S,D] flash call, or
    None when no mesh axis can be used (run unwrapped)."""
    from jax.sharding import PartitionSpec as P

    b_axes = tuple(a for a in ("dcn", "dp", "fsdp")
                   if mesh.shape.get(a, 1) > 1)
    if b_axes and q_shape[0] % math.prod(mesh.shape[a] for a in b_axes):
        b_axes = ()
    tp = mesh.shape.get("tp", 1)
    h_axes = ("tp",) if tp > 1 and q_shape[1] % tp == 0 and \
        kv_shape[1] % tp == 0 else ()
    if not b_axes and not h_axes:
        return None
    return P(b_axes or None, h_axes or None, None, None)


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, Hkv, S, D] -> [B, Hkv*n_rep, S, D] for grouped-query attention."""
    if n_rep == 1:
        return k
    b, hkv, s, d = k.shape
    k = jnp.broadcast_to(k[:, :, None], (b, hkv, n_rep, s, d))
    return k.reshape(b, hkv * n_rep, s, d)


def mha_reference(q: jax.Array,
                  k: jax.Array,
                  v: jax.Array,
                  *,
                  causal: bool = True,
                  sm_scale: Optional[float] = None,
                  segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Stable-softmax attention. q: [B,H,Sq,D]; k,v: [B,Hkv,Sk,D].

    Computes in float32 regardless of input dtype (bf16 inputs hit the MXU
    via preferred_element_type), returns q.dtype.
    """
    *_, h, sq, d = q.shape
    hkv = k.shape[1]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    k = _repeat_kv(k, h // hkv)
    v = _repeat_kv(v, h // hkv)
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else d ** -0.5

    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        qi = jnp.arange(sq)[:, None] + (sk - sq)  # allow kv prefix (decode)
        ki = jnp.arange(sk)[None, :]
        mask = qi >= ki
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask[None, None] & seg)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        # A row with NO unmasked column attends to nothing: define its
        # output (and gradient) as zero, not softmax's accidental
        # uniform distribution over -inf logits. Matches the Pallas
        # kernels' semantics.
        row_live = mask.any(-1, keepdims=True)
        probs = jnp.where(row_live, probs, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def paged_attention(q: jax.Array,
                    k_pages: jax.Array,
                    v_pages: jax.Array,
                    block_tables: jax.Array,
                    q_slots: jax.Array,
                    *,
                    kv_valid_len,
                    sm_scale: Optional[float] = None,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None,
                    impl: str = "auto") -> jax.Array:
    """Attention over PAGED K/V: each query row reads its keys/values
    through a per-row block table instead of a contiguous cache row —
    the vLLM/PagedAttention access pattern, serving the DecodeEngine's
    one-pool-many-requests memory plane.

      q            [B, S, H, D]   queries (S=1 fused decode; S>1 would
                                  be a paged prefill chunk)
      k/v_pages    [NB, T, KV, D] the shared block pool, ONE layer's
                                  slice (the engine scans layers; NB
                                  blocks of T tokens each; block 0 is
                                  the reserved null block)
      block_tables [B, MB]        row b's logical block p covers cache
                                  slots [p*T, (p+1)*T); unallocated
                                  entries point at block 0
      q_slots      [B, S]         the cache slot each query occupies
      kv_valid_len scalar         slots >= this are masked (the
                                  engine's max_len)
      k/v_scale    [NB, KV]       per-block per-kv-head f32 dequant
                                  scales when the pool is quantized
                                  (int8/fp8 — see ops/kv_quant.py);
                                  None for a dense-precision pool

    Semantics are EXACTLY the dense path's `_cached_attention` (see
    models/generate.py) evaluated on the gathered view: causal mask
    ``slot <= q_slot`` plus the valid-length cap, -1e30 fill, f32
    softmax. The two must stay in lockstep op-for-op — the paged
    engine's token-identity to the dense engine and to solo `generate`
    (tests/test_engine_paged.py) rests on it. Positions gathered from
    unallocated/garbage block entries are always masked: exp(-1e30 -
    max) underflows to exactly 0.0, so any finite garbage contributes
    exactly nothing. With scales, dequantization happens INSIDE the
    gather (the pool itself stays quantized; only the per-row view is
    widened, to f32, and XLA fuses it into the einsums).

    ``impl`` mirrors `attention`'s dispatch seam. "reference" is the
    pure-lax lowering above; "flash" routes to the Pallas/Mosaic kernel
    in ops/paged_attention_kernel.py that walks the block table
    block-by-block with an online-softmax inner loop — gather + dequant
    + attend fused, no materialized [B, MB*T, KV, D] view (off-TPU the
    kernel runs in interpret mode, which is how it is unit-tested
    against this reference). "auto" resolves to "flash" on TPU and
    "reference" elsewhere, same policy as `attention`."""
    if impl not in ("auto", "flash", "reference"):
        raise ValueError(f"impl must be auto|flash|reference, got {impl!r}")
    B, S, H, D = q.shape
    NB, T, KV, _ = k_pages.shape
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "reference"
    if impl == "flash":
        from ray_tpu.ops.paged_attention_kernel import paged_attention_kernel

        return paged_attention_kernel(
            q, k_pages, v_pages, block_tables, q_slots,
            kv_valid_len=kv_valid_len, sm_scale=sm_scale,
            k_scale=k_scale, v_scale=v_scale)
    # Gather the per-row dense view: [B, MB, T, KV, D] -> [B, MB*T, ..]
    # (logical slot p*T + t of row b is block_tables[b, p] slot t, so
    # the reshape restores contiguous slot order per row).
    k = k_pages[block_tables]
    v = v_pages[block_tables]
    if k_scale is not None:
        # dequant-in-gather; the view must stay f32 (requantization
        # byte-stability — see ops/kv_quant.py)
        k = k.astype(jnp.float32) * k_scale[block_tables][:, :, None, :,
                                                          None]
        v = v.astype(jnp.float32) * v_scale[block_tables][:, :, None, :,
                                                          None]
    span = k.shape[1] * T
    k = k.reshape(B, span, KV, D)
    v = v.reshape(B, span, KV, D)
    # -- lockstep with generate._cached_attention from here on --
    rep = H // KV
    k = jnp.repeat(k, rep, axis=2)                 # [B, span, H, D]
    v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * (sm_scale if sm_scale is not None else D ** -0.5)
    slots = jnp.arange(span)
    mask = (slots[None, None, None, :] <= q_slots[:, None, :, None]) \
        & (slots[None, None, None, :] < kv_valid_len)
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhst,bthd->bshd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def attention(q: jax.Array,
              k: jax.Array,
              v: jax.Array,
              *,
              causal: bool = True,
              sm_scale: Optional[float] = None,
              impl: str = "auto",
              block_q: Optional[int] = None,
              block_k: Optional[int] = None) -> jax.Array:
    """Dispatch: impl in {'auto', 'flash', 'reference'}. block_q/block_k
    override the flash kernel's tile sizes (None = kernel default);
    ignored on the reference path."""
    for nm, b in (("block_q", block_q), ("block_k", block_k)):
        if b is not None and b <= 0:
            raise ValueError(f"{nm} must be positive, got {b}")
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "reference"
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        if sm_scale is None:
            sm_scale = q.shape[-1] ** -0.5
        # only forward explicit overrides; defaulting stays with the
        # kernel's own signature
        blocks = {k_: v_ for k_, v_ in
                  (("block_q", block_q), ("block_k", block_k))
                  if v_ is not None}
        mesh = _SPMD_MESH.get()
        if mesh is not None and not _in_manual_region():
            spec = _flash_spmd_spec(q.shape, k.shape, mesh)
            if spec is not None:
                from jax import shard_map

                fn = functools.partial(flash_attention, causal=causal,
                                       sm_scale=sm_scale, **blocks)
                return shard_map(fn, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False)(q, k, v)
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               **blocks)
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
