"""TuneController — the experiment event loop.

Reference: python/ray/tune/execution/tune_controller.py:68. Drives trials
as ray_tpu actors (one TrainableActor per running trial), stepwise: each
``train()`` actor call produces one result; the controller feeds it to the
searcher + scheduler, applies stop criteria, and handles PBT
exploit/explore via checkpoint transfer between actors. Failed trials
restart from their latest checkpoint up to FailureConfig.max_failures.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.experiment import (ERROR, PENDING, RUNNING, TERMINATED,
                                     Trial)
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import TrainableActor


def _latest_checkpoint_dir(trial_dir: str) -> Optional[str]:
    """Newest checkpoint_NNNNNN dir under a trial dir (on-disk recovery
    of a RUNNING trial's progress after a driver crash)."""
    try:
        ckpts = sorted(d for d in os.listdir(trial_dir)
                       if d.startswith("checkpoint_"))
    except OSError:
        return None
    return os.path.join(trial_dir, ckpts[-1]) if ckpts else None


class TuneController:
    def __init__(self,
                 trainable_cls: type,
                 param_space: Dict,
                 *,
                 num_samples: int = 1,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 scheduler: Optional[TrialScheduler] = None,
                 search_alg: Optional[Searcher] = None,
                 max_concurrent_trials: int = 0,
                 experiment_dir: str = "",
                 stop: Optional[Dict] = None,
                 max_failures: int = 0,
                 trial_resources: Optional[Dict[str, float]] = None,
                 callbacks: Optional[List] = None,
                 restored_trials: Optional[List[Trial]] = None):
        self.trainable_cls = trainable_cls
        self.metric, self.mode = metric, mode
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(metric, mode)
        self.search_alg = search_alg or BasicVariantGenerator()
        self.search_alg.set_search_properties(metric, mode, param_space)
        self.stop = stop or {}
        self.max_failures = max_failures if max_failures >= 0 else 10 ** 9
        self.experiment_dir = experiment_dir
        os.makedirs(experiment_dir, exist_ok=True)
        self.trial_resources = trial_resources or {"CPU": 1.0}
        from ray_tpu.tune.logger import DEFAULT_CALLBACKS

        self.callbacks = callbacks if callbacks is not None else \
            [cls() for cls in DEFAULT_CALLBACKS]

        # Pending configs: grid/random searchers pre-generate; adaptive
        # searchers are polled via suggest() as slots open. Unwrap
        # ConcurrencyLimiter so a wrapped BasicVariantGenerator still takes
        # the pre-generation path (its suggest() yields nothing).
        from ray_tpu.tune.search.searcher import ConcurrencyLimiter

        base_searcher = self.search_alg
        limiter_cap = None
        while isinstance(base_searcher, ConcurrencyLimiter):
            limiter_cap = (base_searcher.max_concurrent
                           if limiter_cap is None
                           else min(limiter_cap, base_searcher.max_concurrent))
            base_searcher = base_searcher.searcher
        self._pending: List[Trial] = []
        self._adaptive = not isinstance(base_searcher, BasicVariantGenerator)
        self._restored: List[Trial] = []
        if restored_trials is not None:
            # Experiment-level resume (reference: tuner.py:243
            # Tuner.restore): finished trials keep their results;
            # unfinished ones re-queue and resume from their latest
            # checkpoint. No NEW samples are generated.
            self._adaptive = False
            self._remaining_suggestions = 0
            for t in restored_trials:
                if t.status in (TERMINATED, ERROR):
                    self._restored.append(t)
                else:
                    t.status = PENDING
                    self._pending.append(t)
        elif self._adaptive:
            self._remaining_suggestions = num_samples
        else:
            for cfg in base_searcher.generate_variants(
                    param_space, num_samples):
                self._pending.append(Trial(cfg, experiment_dir))
        if max_concurrent_trials <= 0:
            ncpu = os.cpu_count() or 8
            max_concurrent_trials = max(1, min(16, ncpu))
        if limiter_cap is not None:
            max_concurrent_trials = min(max_concurrent_trials, limiter_cap)
        self.max_concurrent = max_concurrent_trials

        self.trials: List[Trial] = self._restored + list(self._pending)
        self._actors: Dict[str, object] = {}        # trial_id -> handle
        self._inflight: Dict[object, Trial] = {}    # train() ref -> trial
        self._actor_cls = ray_tpu.remote(TrainableActor)
        self._last_snapshot = 0.0

    # ------------------------------------------------- experiment snapshot
    SNAPSHOT_FILE = "experiment_state.pkl"

    def save_experiment_state(self) -> None:
        """Atomic snapshot of every trial's progress (reference:
        tune/execution/experiment_state.py) — Tuner.restore() resumes
        from it after a driver crash. Result histories are truncated
        (full per-result streams already persist via the logger
        callbacks); the snapshot cost stays flat as experiments age."""
        import dataclasses as _dc

        import cloudpickle

        slim = [_dc.replace(t, results=t.results[-1:])
                for t in self.trials]
        path = os.path.join(self.experiment_dir, self.SNAPSHOT_FILE)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump({"trials": slim}, f)
        os.replace(tmp, path)

    @staticmethod
    def load_experiment_state(experiment_dir: str) -> List[Trial]:
        import cloudpickle

        path = os.path.join(experiment_dir,
                            TuneController.SNAPSHOT_FILE)
        with open(path, "rb") as f:
            trials = cloudpickle.load(f)["trials"]
        for t in trials:
            if t.status not in (TERMINATED, ERROR) and \
                    not t.checkpoint_path:
                # A RUNNING trial's snapshot rarely carries its newest
                # checkpoint — recover it from the trial dir on disk so
                # resume continues instead of restarting.
                t.checkpoint_path = _latest_checkpoint_dir(t.trial_dir)
        return trials

    # ------------------------------------------------------------------
    def _launch(self, trial: Trial, restore_from: Optional[str] = None):
        if restore_from is None and not trial.results:
            for cb in self.callbacks:
                try:
                    cb.on_trial_start(trial)
                except Exception:
                    pass
        res = trial.resources or self.trial_resources
        opts = {"num_cpus": res.get("CPU", 1.0)}
        custom = {k: v for k, v in res.items()
                  if k != "CPU"}
        if "TPU" in custom:
            opts["num_tpus"] = custom.pop("TPU")
        if custom:
            opts["resources"] = custom
        handle = self._actor_cls.options(**opts).remote(
            self.trainable_cls, trial.config, trial.trial_dir,
            restore_from=restore_from or trial.checkpoint_path,
            trial_resources=dict(res))
        if trial.status == PENDING:
            # First start (not a PBT-exploit restart): let the scheduler
            # register it (HyperBand bracket membership).
            self.scheduler.on_trial_add(self, trial)
        trial.status = RUNNING
        self._actors[trial.trial_id] = handle
        ref = handle.train.remote()
        self._inflight[ref] = trial

    def _stop_actor(self, trial: Trial):
        handle = self._actors.pop(trial.trial_id, None)
        if handle is None:
            return
        try:
            ray_tpu.get(handle.stop.remote(), timeout=5)
        except Exception:
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass
        self._inflight = {r: t for r, t in self._inflight.items()
                          if t.trial_id != trial.trial_id}

    def has_pending_trials(self) -> bool:
        """More trials will still start (schedulers use this to decide
        whether a bracket/cohort can still grow)."""
        if self._pending:
            return True
        return bool(self._adaptive and self._remaining_suggestions > 0
                    and not getattr(self, "_searcher_exhausted", False))

    def _next_trial(self) -> Optional[Trial]:
        if self._pending:
            return self._pending.pop(0)
        if self._adaptive and self._remaining_suggestions > 0:
            t = Trial({}, self.experiment_dir)
            cfg = self.search_alg.suggest(t.trial_id)
            if cfg is None:
                # Exhausted (the controller only polls within the
                # concurrency cap, so None ≈ no more configs): stop
                # telling schedulers more trials are coming, or a
                # below-capacity HyperBand bracket would never halve.
                self._searcher_exhausted = True
                return None
            self._remaining_suggestions -= 1
            t.config = cfg
            self.trials.append(t)
            return t
        return None

    def _should_stop(self, result: Dict) -> bool:
        if result.get("done"):
            return True
        for k, v in self.stop.items():
            if k in result and result[k] >= v:
                return True
        return False

    # ------------------------------------------------------------------
    def exploit(self, trial: Trial, donor_id: str,
                explore_fn: Callable[[Dict], Dict]) -> None:
        """PBT: restart `trial` from `donor`'s checkpoint with a mutated
        config (reference pbt.py _exploit)."""
        donor = next((t for t in self.trials if t.trial_id == donor_id), None)
        if donor is None:
            return
        donor_handle = self._actors.get(donor_id)
        ckpt = None
        if donor_handle is not None:
            try:
                ckpt = ray_tpu.get(donor_handle.save.remote(), timeout=60)
            except Exception:
                ckpt = donor.checkpoint_path
        else:
            ckpt = donor.checkpoint_path
        if not ckpt:
            return
        donor.checkpoint_path = ckpt
        new_config = explore_fn(donor.config)
        self._stop_actor(trial)
        trial.config = new_config
        trial.checkpoint_path = ckpt
        self._launch(trial, restore_from=ckpt)

    # ------------------------------------------------------------------
    def reallocate(self, trial: Trial,
                   resources: Dict[str, float]) -> None:
        """Restart a running trial with new resources, resuming from its
        latest checkpoint (reference: resource_changing_scheduler.py —
        the trial is paused and its placement group replaced)."""
        trial.resources = dict(resources)
        handle = self._actors.get(trial.trial_id)
        if handle is None:
            return  # not running: the next launch picks the override up
        ckpt = None
        try:
            ckpt = ray_tpu.get(handle.save.remote(), timeout=60)
        except Exception:
            ckpt = trial.checkpoint_path
        if not ckpt:
            # A checkpoint-less trainable cannot be paused without
            # losing its progress: keep it running at the old
            # allocation (trial.resources applies to any LATER
            # restart) rather than silently rerunning from scratch.
            return
        self._stop_actor(trial)
        trial.checkpoint_path = ckpt
        self._launch(trial, restore_from=ckpt)

    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One controller iteration. Returns False when the run is over."""
        # fill open slots
        while len(self._actors) < self.max_concurrent:
            trial = self._next_trial()
            if trial is None:
                break
            self._launch(trial)

        if not self._inflight:
            return False

        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=60.0)
        for ref in ready:
            # A trial processed earlier in this batch may have exploited
            # this one, dropping its in-flight ref.
            trial = self._inflight.pop(ref, None)
            if trial is None or trial.trial_id not in self._actors:
                continue
            handle = self._actors[trial.trial_id]
            try:
                result = ray_tpu.get(ref)
            except Exception as e:  # trial crashed
                trial.num_failures += 1
                self.search_alg.on_trial_result(trial.trial_id,
                                                {"error": str(e)})
                # The actor may still be alive (user code raised): grab its
                # latest checkpoint so the restart resumes instead of
                # starting over.
                try:
                    ckpt = ray_tpu.get(
                        handle.latest_checkpoint.remote(), timeout=30)
                    if ckpt:
                        trial.checkpoint_path = ckpt
                except Exception:
                    pass
                self._stop_actor(trial)
                if trial.num_failures <= self.max_failures:
                    self._launch(trial)  # restart from latest checkpoint
                else:
                    trial.status = ERROR
                    trial.error = str(e)
                    self.search_alg.on_trial_complete(
                        trial.trial_id, error=True)
                    # Schedulers must drop it too (e.g. a HyperBand
                    # bracket waiting on this member would never halve).
                    self.scheduler.on_trial_complete(
                        self, trial, trial.last_result or {})
                continue

            # Merge so the bare {"done": True} end-of-function sentinel
            # doesn't clobber the last real metrics.
            trial.last_result = {**trial.last_result, **result}
            trial.results.append(result)
            for cb in self.callbacks:
                try:
                    cb.on_trial_result(trial, result)
                except Exception:
                    pass
            self.search_alg.on_trial_result(trial.trial_id, result)
            decision = self.scheduler.on_trial_result(self, trial, result)
            if self._should_stop(result) or decision == sched_mod.STOP:
                # capture the final checkpoint before teardown
                try:
                    ckpt = ray_tpu.get(
                        handle.latest_checkpoint.remote(), timeout=30)
                    if ckpt:
                        trial.checkpoint_path = ckpt
                except Exception:
                    pass
                trial.status = TERMINATED
                for cb in self.callbacks:
                    try:
                        cb.on_trial_complete(trial)
                    except Exception:
                        pass
                self.search_alg.on_trial_complete(trial.trial_id, result)
                self.scheduler.on_trial_complete(self, trial, result)
                self._stop_actor(trial)
            else:
                # exploit() may have relaunched this trial during
                # scheduler.on_trial_result, already enqueuing a train()
                # ref — don't double-schedule on the fresh actor.
                has_inflight = any(t.trial_id == trial.trial_id
                                   for t in self._inflight.values())
                if trial.trial_id in self._actors and not has_inflight:
                    nref = self._actors[trial.trial_id].train.remote()
                    self._inflight[nref] = trial
        return bool(self._inflight or self._pending or
                    (self._adaptive and self._remaining_suggestions > 0))

    def run(self) -> List[Trial]:
        import time as _time

        try:
            while self.step():
                now = _time.monotonic()
                if now - self._last_snapshot > 1.0:
                    self._last_snapshot = now
                    try:
                        self.save_experiment_state()
                    except Exception:
                        pass
        finally:
            # Snapshot BEFORE flipping RUNNING -> TERMINATED: an
            # interrupted run must leave a snapshot whose unfinished
            # trials are still marked unfinished, or restore() would
            # treat their partial results as final.
            try:
                self.save_experiment_state()
            except Exception:
                pass
            for trial in self.trials:
                if trial.status == RUNNING:
                    trial.status = TERMINATED
                self._stop_actor(trial)
            for cb in self.callbacks:
                try:
                    cb.on_experiment_end(self.trials)
                except Exception:
                    pass
        return self.trials
