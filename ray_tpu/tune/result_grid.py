"""ResultGrid — results of a Tuner.fit().

Reference: python/ray/tune/result_grid.py (get_best_result,
get_dataframe, indexing).
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.air.result import Result
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune.experiment import ERROR, Trial


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self._trials = trials
        self._metric, self._mode = metric, mode
        self._results = [self._to_result(t) for t in trials]

    @staticmethod
    def _to_result(trial: Trial) -> Result:
        metrics = dict(trial.last_result)
        metrics["config"] = trial.config
        metrics["trial_id"] = trial.trial_id
        ckpt = Checkpoint(trial.checkpoint_path) \
            if trial.checkpoint_path else None
        err = RuntimeError(trial.error) if trial.error else None
        # Per-iteration history (reference: Result.metrics_dataframe from
        # the trial's progress.csv). Nested values (sub-dicts) are
        # dropped — the dataframe is for scalar metric curves.
        df = None
        if trial.results:
            try:
                import pandas as pd

                df = pd.DataFrame(
                    [{k: v for k, v in r.items()
                      if not isinstance(v, (dict, list))}
                     for r in trial.results])
            except ImportError:
                pass
        return Result(metrics=metrics, checkpoint=ckpt, error=err,
                      path=trial.trial_dir, metrics_dataframe=df)

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def num_errors(self) -> int:
        return sum(1 for t in self._trials if t.status == ERROR)

    @property
    def num_terminated(self) -> int:
        return len(self._trials) - self.num_errors

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None,
                        scope: str = "last") -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric is required (set in TuneConfig or "
                             "pass explicitly)")
        sign = 1 if mode == "max" else -1

        def key(pair):
            trial, _ = pair
            if scope == "all":
                v = trial.best_metric(metric, mode)
            else:
                v = trial.last_result.get(metric)
                v = v if isinstance(v, (int, float)) else None
            return -float("inf") if v is None else sign * v

        trial, result = max(zip(self._trials, self._results), key=key)
        return result

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = {k: v for k, v in t.last_result.items()
                   if not isinstance(v, (dict, list))}
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            for k, v in t.config.items():
                if not isinstance(v, (dict, list)):
                    row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)
