"""HyperOpt searcher adapter (gated).

Reference: python/ray/tune/search/hyperopt/hyperopt_search.py — an
adapter over hyperopt's TPE: the tune search space converts to `hp.*`
expressions, suggestions come from `tpe.suggest` against a live
`Trials` book, and completions are written back as hyperopt results.
hyperopt is an optional dependency: importing this module always works;
constructing `HyperOptSearch` without it raises with install guidance.
The in-tree, dependency-free TPE lives in
ray_tpu.tune.search.optuna.TuneTPE.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _to_hyperopt_space(space: Dict[str, Any]):
    from hyperopt import hp

    out = {}
    for name, dom in sorted(space.items()):
        if isinstance(dom, Categorical):
            out[name] = hp.choice(name, list(dom.categories))
        elif isinstance(dom, Float):
            if dom.log:
                import numpy as np

                out[name] = hp.loguniform(name, np.log(dom.lower),
                                          np.log(dom.upper))
            else:
                out[name] = hp.uniform(name, dom.lower, dom.upper)
        elif isinstance(dom, Integer):
            out[name] = hp.uniformint(name, dom.lower, dom.upper - 1)
        else:
            raise ValueError(
                f"HyperOptSearch cannot express domain {dom!r} "
                f"for {name!r}")
    return out


class HyperOptSearch(Searcher):
    def __init__(self,
                 space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 n_initial_points: int = 20,
                 random_state_seed: Optional[int] = None):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires the 'hyperopt' package "
                "(pip install hyperopt); for a dependency-free TPE "
                "searcher use ray_tpu.tune.search.optuna.TuneTPE") from e
        import functools

        import numpy as np
        from hyperopt import tpe

        super().__init__(metric, mode)
        self._metric = metric
        self._mode = mode
        self._space = dict(space or {})
        self._fixed: Dict[str, Any] = {}
        self._suggest_fn = functools.partial(
            tpe.suggest, n_startup_jobs=n_initial_points)
        self._rng = np.random.default_rng(random_state_seed)
        self._trials = None       # hyperopt.Trials, lazily created
        self._domain = None
        self._hp_space = None     # cached hp.* expression graph
        self._live: Dict[str, int] = {}  # trial_id -> hyperopt tid

    def set_search_properties(self, metric, mode, config=None) -> None:
        self._metric = metric or self._metric
        self._mode = mode or self._mode
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
            self._fixed = {k: v for k, v in config.items()
                           if not isinstance(v, Domain)}

    def _ensure_book(self) -> None:
        import hyperopt

        if self._trials is None:
            self._trials = hyperopt.Trials()
            self._hp_space = _to_hyperopt_space(self._space)
            self._domain = hyperopt.Domain(lambda spc: spc,
                                           self._hp_space)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        import hyperopt

        self._ensure_book()
        new_ids = self._trials.new_trial_ids(1)
        self._trials.refresh()
        seed = int(self._rng.integers(2 ** 31 - 1))
        new_trials = self._suggest_fn(new_ids, self._domain, self._trials,
                                      seed)
        self._trials.insert_trial_docs(new_trials)
        self._trials.refresh()
        tid = new_trials[0]["tid"]
        self._live[trial_id] = tid
        vals = {k: v[0] for k, v in
                new_trials[0]["misc"]["vals"].items() if v}
        config = hyperopt.space_eval(self._hp_space, vals)
        return {**self._fixed, **config}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        import hyperopt

        tid = self._live.pop(trial_id, None)
        if tid is None or self._trials is None:
            return
        for doc in self._trials.trials:
            if doc["tid"] != tid:
                continue
            if error or not result or self._metric not in result:
                doc["state"] = hyperopt.JOB_STATE_ERROR
            else:
                value = float(result[self._metric])
                loss = -value if self._mode == "max" else value
                doc["state"] = hyperopt.JOB_STATE_DONE
                doc["result"] = {"loss": loss, "status": "ok"}
            break
        self._trials.refresh()
