"""HEBO searcher adapter (gated).

Reference: python/ray/tune/search/hebo/hebo_search.py — an ask/tell
adapter over Huawei Noah's Ark HEBO (Heteroscedastic Evolutionary
Bayesian Optimization). The tune search space converts to a HEBO
DesignSpace; `suggest` asks for a candidate DataFrame row,
`on_trial_complete` observes the loss back. hebo is an optional
dependency: importing this module always works; constructing
`HEBOSearch` without it raises with install guidance.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _to_hebo_space(space: Dict[str, Any]) -> list:
    specs = []
    for name, dom in sorted(space.items()):
        if isinstance(dom, Categorical):
            specs.append({"name": name, "type": "cat",
                          "categories": list(dom.categories)})
        elif isinstance(dom, Float):
            specs.append({"name": name,
                          "type": "pow" if dom.log else "num",
                          "lb": dom.lower, "ub": dom.upper})
        elif isinstance(dom, Integer):
            specs.append({"name": name, "type": "int",
                          "lb": dom.lower, "ub": dom.upper - 1})
        else:
            raise ValueError(
                f"HEBOSearch cannot express domain {dom!r} for {name!r}")
    return specs


class HEBOSearch(Searcher):
    def __init__(self,
                 space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 random_state_seed: Optional[int] = None):
        try:
            import hebo  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HEBOSearch requires the 'hebo' package "
                "(pip install HEBO); for a dependency-free Bayesian "
                "searcher use "
                "ray_tpu.tune.search.bayesopt.BayesOptSearch") from e
        super().__init__(metric, mode)
        self._metric = metric
        self._mode = mode
        self._space = dict(space or {})
        self._fixed: Dict[str, Any] = {}
        self._seed = random_state_seed
        self._opt = None
        self._live: Dict[str, Any] = {}  # trial_id -> candidate row

    def set_search_properties(self, metric, mode, config=None) -> None:
        self._metric = metric or self._metric
        self._mode = mode or self._mode
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
            self._fixed = {k: v for k, v in config.items()
                           if not isinstance(v, Domain)}

    def _ensure_optimizer(self) -> None:
        if self._opt is not None:
            return
        from hebo.design_space.design_space import DesignSpace
        from hebo.optimizers.hebo import HEBO

        ds = DesignSpace().parse(_to_hebo_space(self._space))
        kwargs = {}
        if self._seed is not None:
            kwargs["scramble_seed"] = self._seed
        self._opt = HEBO(ds, **kwargs)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        self._ensure_optimizer()
        candidate = self._opt.suggest(n_suggestions=1)
        self._live[trial_id] = candidate
        row = candidate.iloc[0].to_dict()
        return {**self._fixed, **row}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        import numpy as np

        candidate = self._live.pop(trial_id, None)
        if candidate is None or self._opt is None:
            return
        if error or not result or self._metric not in result:
            return
        value = float(result[self._metric])
        loss = -value if self._mode == "max" else value
        self._opt.observe(candidate, np.array([[loss]]))
