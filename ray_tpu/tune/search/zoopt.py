"""ZOOpt searcher adapter (gated).

Reference: python/ray/tune/search/zoopt/zoopt_search.py — an adapter
over ZOOpt's SRacos (sequential randomized coordinate shrinking), which
supports an ask/tell flow through `SRacosTune.suggest`/`complete`. The
tune search space converts to a `zoopt.Dimension2` spec. zoopt is an
optional dependency: importing this module always works; constructing
`ZOOptSearch` without it raises with install guidance.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _to_zoopt_dim(space: Dict[str, Any]):
    from zoopt import ValueType

    names, dims = [], []
    for name, dom in sorted(space.items()):
        names.append(name)
        if isinstance(dom, Categorical):
            dims.append((ValueType.GRID, list(dom.categories)))
        elif isinstance(dom, Float):
            dims.append((ValueType.CONTINUOUS, [dom.lower, dom.upper],
                         1e-10))
        elif isinstance(dom, Integer):
            dims.append((ValueType.DISCRETE, [dom.lower, dom.upper - 1],
                         False))
        else:
            raise ValueError(
                f"ZOOptSearch cannot express domain {dom!r} for {name!r}")
    return names, dims


class ZOOptSearch(Searcher):
    def __init__(self,
                 space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 budget: int = 100,
                 parallel_num: int = 1):
        try:
            import zoopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "ZOOptSearch requires the 'zoopt' package "
                "(pip install zoopt); dependency-free alternatives: "
                "BasicVariantGenerator (random/grid) or BayesOptSearch "
                "(GP-UCB)") from e
        super().__init__(metric, mode)
        self._metric = metric
        self._mode = mode
        self._space = dict(space or {})
        self._fixed: Dict[str, Any] = {}
        self._budget = budget
        self._parallel_num = parallel_num
        self._core = None      # SRacosTune
        self._names = None
        self._live: Dict[str, Any] = {}  # trial_id -> zoopt Solution

    def set_search_properties(self, metric, mode, config=None) -> None:
        self._metric = metric or self._metric
        self._mode = mode or self._mode
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
            self._fixed = {k: v for k, v in config.items()
                           if not isinstance(v, Domain)}

    def _ensure_core(self) -> None:
        if self._core is not None:
            return
        from zoopt import Dimension2, Parameter
        from zoopt.algos.opt_algorithms.racos.sracos import SRacosTune

        self._names, dims = _to_zoopt_dim(self._space)
        # Call shape per the reference adapter (zoopt_search.py):
        # SRacosTune(dimension=..., parameter=..., parallel_num=...).
        self._core = SRacosTune(
            dimension=Dimension2(dims),
            parameter=Parameter(budget=self._budget),
            parallel_num=self._parallel_num)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        self._ensure_core()
        solution = self._core.suggest()
        if solution is None:
            return None
        self._live[trial_id] = solution
        values = solution.get_x()
        return {**self._fixed, **dict(zip(self._names, values))}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        solution = self._live.pop(trial_id, None)
        if solution is None or self._core is None:
            return
        if error or not result or self._metric not in result:
            return
        value = float(result[self._metric])
        # SRacos minimizes.
        self._core.complete(solution,
                            -value if self._mode == "max" else value)
