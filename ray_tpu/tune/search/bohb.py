"""TuneBOHB — model-based search for the HyperBandForBOHB scheduler.

Reference: python/ray/tune/search/bohb/bohb_search.py (TuneBOHB wraps the
hpbandster KDE model). Redesign without the dependency: a TPE-style
density-ratio sampler in plain numpy — observed configs are split into a
good (top-gamma) and bad set per the metric, Gaussian KDEs are fit to
both over the normalized numeric dimensions, and candidates maximizing
good-density / bad-density are suggested. Categorical dimensions use
smoothed frequency ratios.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class TuneBOHB(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 gamma: float = 0.25, min_points: int = 8,
                 n_candidates: int = 64, seed: int = 0):
        super().__init__(metric, mode)
        self._space = dict(space or {})
        self.gamma = gamma
        self.min_points = min_points
        self.n_candidates = n_candidates
        self._rng = np.random.default_rng(seed)
        self._live: Dict[str, Dict] = {}
        self._observed: List = []  # (config, score)

    def set_search_properties(self, metric, mode, config) -> bool:
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
        return super().set_search_properties(metric, mode, config)

    # ---- encoding ----
    def _numeric_domains(self):
        return [(k, d) for k, d in self._space.items()
                if isinstance(d, (Float, Integer))]

    def _encode(self, config: Dict) -> np.ndarray:
        vec = []
        for k, d in self._numeric_domains():
            v = float(config[k])
            lo, hi = float(d.lower), float(d.upper)
            if getattr(d, "log", False):
                v, lo, hi = np.log(v), np.log(lo), np.log(hi)
            vec.append((v - lo) / max(hi - lo, 1e-12))
        return np.asarray(vec)

    def _sample_config(self) -> Dict:
        return {k: d.sample(self._rng) if isinstance(d, Domain) else d
                for k, d in self._space.items()}

    # ---- Searcher API ----
    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self._observed) < self.min_points or \
                not self._numeric_domains():
            cfg = self._sample_config()
            self._live[trial_id] = cfg
            return cfg
        scores = np.asarray([s for _, s in self._observed])
        order = np.argsort(-scores)  # maximize internal score
        n_good = max(2, int(len(order) * self.gamma))
        good = [self._observed[i][0] for i in order[:n_good]]
        bad = [self._observed[i][0] for i in order[n_good:]] or good
        Xg = np.stack([self._encode(c) for c in good])
        Xb = np.stack([self._encode(c) for c in bad])
        bw = max(0.1, 1.0 / np.sqrt(len(Xg)))

        def kde(X, pts):
            d2 = ((pts[:, None, :] - X[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * bw * bw)).mean(1) + 1e-12

        candidates = [self._sample_config()
                      for _ in range(self.n_candidates)]
        # Bias half the candidates toward the good set (TPE style):
        # jitter around randomly-chosen good points.
        numeric = self._numeric_domains()
        for i in range(self.n_candidates // 2):
            base = good[int(self._rng.integers(len(good)))]
            cand = dict(candidates[i])
            for k, d in numeric:
                lo, hi = float(d.lower), float(d.upper)
                span = hi - lo
                v = float(base[k]) + float(self._rng.normal(0, 0.1 * span))
                v = min(hi, max(lo, v))
                cand[k] = int(round(v)) if isinstance(d, Integer) else v
            candidates[i] = cand
        pts = np.stack([self._encode(c) for c in candidates])
        ratio = kde(Xg, pts) / kde(Xb, pts)
        # Categorical dims: smoothed frequency ratio.
        for k, d in self._space.items():
            if not isinstance(d, Categorical):
                continue
            freq_g: Dict = {}
            freq_b: Dict = {}
            for c in good:
                freq_g[c[k]] = freq_g.get(c[k], 0) + 1
            for c in bad:
                freq_b[c[k]] = freq_b.get(c[k], 0) + 1
            for i, c in enumerate(candidates):
                g = (freq_g.get(c[k], 0) + 1) / (len(good) + len(
                    d.categories))
                b = (freq_b.get(c[k], 0) + 1) / (len(bad) + len(
                    d.categories))
                ratio[i] *= g / b
        cfg = candidates[int(np.argmax(ratio))]
        self._live[trial_id] = cfg
        return cfg

    def _internal_score(self, result: Dict) -> Optional[float]:
        v = result.get(self.metric) if result else None
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        # Keep only the latest score per live trial (refreshed on
        # completion below).
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None,
                          error: bool = False) -> None:
        cfg = self._live.pop(trial_id, None)
        score = self._internal_score(result)
        if cfg is not None and score is not None and not error:
            self._observed.append((cfg, score))
