"""Nevergrad searcher adapter (gated).

Reference: python/ray/tune/search/nevergrad/nevergrad_search.py — an
ask/tell adapter over Meta's nevergrad optimizers. The tune search space
converts to an `ng.p.Dict` parametrization; `suggest` asks the
optimizer for a candidate, `on_trial_complete` tells the loss back.
nevergrad is an optional dependency: importing this module always
works; constructing `NevergradSearch` without it raises with install
guidance.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _to_nevergrad_parametrization(space: Dict[str, Any]):
    import nevergrad as ng

    params = {}
    for name, dom in sorted(space.items()):
        if isinstance(dom, Categorical):
            params[name] = ng.p.Choice(list(dom.categories))
        elif isinstance(dom, Float):
            if dom.log:
                params[name] = ng.p.Log(lower=dom.lower, upper=dom.upper)
            else:
                params[name] = ng.p.Scalar(lower=dom.lower,
                                           upper=dom.upper)
        elif isinstance(dom, Integer):
            params[name] = ng.p.Scalar(
                lower=dom.lower, upper=dom.upper - 1
            ).set_integer_casting()
        else:
            raise ValueError(
                f"NevergradSearch cannot express domain {dom!r} "
                f"for {name!r}")
    return ng.p.Dict(**params)


class NevergradSearch(Searcher):
    def __init__(self,
                 space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 optimizer: str = "NGOpt",
                 budget: int = 100):
        try:
            import nevergrad  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "NevergradSearch requires the 'nevergrad' package "
                "(pip install nevergrad); dependency-free alternatives: "
                "BasicVariantGenerator (random/grid) or BayesOptSearch "
                "(GP-UCB)") from e
        super().__init__(metric, mode)
        self._metric = metric
        self._mode = mode
        self._space = dict(space or {})
        self._fixed: Dict[str, Any] = {}
        self._optimizer_name = optimizer
        self._budget = budget
        self._opt = None
        self._live: Dict[str, Any] = {}  # trial_id -> candidate

    def set_search_properties(self, metric, mode, config=None) -> None:
        self._metric = metric or self._metric
        self._mode = mode or self._mode
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
            self._fixed = {k: v for k, v in config.items()
                           if not isinstance(v, Domain)}

    def _ensure_optimizer(self) -> None:
        import nevergrad as ng

        if self._opt is None:
            cls = ng.optimizers.registry[self._optimizer_name]
            self._opt = cls(
                parametrization=_to_nevergrad_parametrization(
                    self._space),
                budget=self._budget)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        self._ensure_optimizer()
        candidate = self._opt.ask()
        self._live[trial_id] = candidate
        return {**self._fixed, **dict(candidate.value)}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        candidate = self._live.pop(trial_id, None)
        if candidate is None or self._opt is None:
            return
        if error or not result or self._metric not in result:
            return  # dropped candidates simply never get told
        value = float(result[self._metric])
        loss = -value if self._mode == "max" else value
        self._opt.tell(candidate, loss)
