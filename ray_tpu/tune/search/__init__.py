from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.sample import (Categorical, Domain, Float, Integer,
                                        choice, grid_search, lograndint,
                                        loguniform, qloguniform, quniform,
                                        randint, randn, sample_from, uniform)
from ray_tpu.tune.search.bayesopt import BayesOptSearch
from ray_tpu.tune.search.bohb import TuneBOHB
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher

__all__ = [
    "BayesOptSearch",
    "BasicVariantGenerator", "Categorical", "ConcurrencyLimiter", "Domain",
    "Float", "Integer", "Searcher", "choice", "grid_search", "lograndint",
    "loguniform", "qloguniform", "quniform", "randint", "randn",
    "sample_from", "uniform", "TuneBOHB",
]
