"""OptunaSearch — adapter to the optuna library when it is installed.

Reference: python/ray/tune/search/optuna/optuna_search.py. The adapter
interface exists unconditionally (so configs referencing it parse and
error messages are actionable); construction raises ImportError in
hermetic images without optuna.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class OptunaSearch(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 sampler=None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the `optuna` package, which is not "
                "available in this environment. Use TuneBOHB "
                "(ray_tpu.tune.search.bohb.TuneBOHB) for a built-in "
                "model-based searcher, or install optuna.") from e
        self._optuna = optuna
        self._space = dict(space or {})
        self._sampler = sampler
        self._seed = seed
        # Created lazily at the first suggest(): the real mode may only
        # arrive via set_search_properties (TuneConfig(mode=...)), and the
        # study direction is immutable after creation.
        self._study = None
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
        return super().set_search_properties(metric, mode, config)

    def _ensure_study(self):
        if self._study is None:
            optuna = self._optuna
            direction = ("maximize" if (self.mode or "max") == "max"
                         else "minimize")
            self._study = optuna.create_study(
                direction=direction,
                sampler=self._sampler or
                optuna.samplers.TPESampler(seed=self._seed))
        return self._study

    def _suggest_param(self, trial, name: str, domain: Domain):
        if isinstance(domain, Float):
            q = getattr(domain, "q", None)
            if q and not domain.log:  # optuna forbids step with log
                return trial.suggest_float(name, domain.lower,
                                           domain.upper, step=q)
            return trial.suggest_float(name, domain.lower, domain.upper,
                                       log=bool(domain.log))
        if isinstance(domain, Integer):
            return trial.suggest_int(name, domain.lower, domain.upper - 1,
                                     log=bool(domain.log))
        if isinstance(domain, Categorical):
            return trial.suggest_categorical(name, domain.categories)
        raise TypeError(f"unsupported domain for optuna: {domain!r}")

    def suggest(self, trial_id: str) -> Optional[Dict]:
        trial = self._ensure_study().ask()
        self._trials[trial_id] = trial
        return {name: (self._suggest_param(trial, name, d)
                       if isinstance(d, Domain) else d)
                for name, d in self._space.items()}

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None,
                          error: bool = False) -> None:
        trial = self._trials.pop(trial_id, None)
        if trial is None:
            return
        study = self._ensure_study()
        if error or not result or self.metric not in result:
            study.tell(trial, state=self._optuna.trial.TrialState.FAIL)
        else:
            study.tell(trial, float(result[self.metric]))
