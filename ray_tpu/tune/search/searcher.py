"""Searcher plugin interface + ConcurrencyLimiter.

Reference: python/ray/tune/search/searcher.py (Searcher base: suggest /
on_trial_result / on_trial_complete) and concurrency_limiter.py. Adaptive
searchers (Optuna-style TPE, bayesopt, ...) plug in by implementing
``suggest``; grid/random search lives in BasicVariantGenerator which
pre-generates variants instead.
"""

from __future__ import annotations

from typing import Dict, Optional


class Searcher:
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str], config: Dict) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        """Return a config for a new trial, or None if exhausted."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from a wrapped searcher."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None,
                          error: bool = False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
