"""Grid/random variant generation.

Reference: python/ray/tune/search/basic_variant.py (BasicVariantGenerator)
and variant_generator.py (grid expansion). A param_space is a (possibly
nested) dict whose leaves may be plain values, Domain samplers, or
``grid_search`` marker dicts. The generator yields num_samples copies of
the full grid cross-product, sampling the Domain leaves independently for
each variant.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.search.searcher import Searcher


def _find_grid_leaves(space: Dict, path=()) -> List[Tuple[Tuple, List]]:
    out = []
    for k, v in space.items():
        if isinstance(v, dict) and "grid_search" in v and \
                len(v) == 1 and isinstance(v["grid_search"], list):
            out.append((path + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            out.extend(_find_grid_leaves(v, path + (k,)))
    return out


def _set_path(d: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def _sample_leaves(space: Any, rng: np.random.Generator) -> Any:
    if isinstance(space, Domain):
        return space.sample(rng)
    if isinstance(space, dict):
        return {k: _sample_leaves(v, rng) for k, v in space.items()}
    return space


class BasicVariantGenerator(Searcher):
    """Exhaustive grid cross-product × num_samples random samples."""

    def __init__(self, max_concurrent: int = 0, random_state: int = 0):
        super().__init__()
        self.max_concurrent = max_concurrent
        self._rng = np.random.default_rng(random_state or None)

    def generate_variants(self, param_space: Dict,
                          num_samples: int) -> Iterator[Dict]:
        grids = _find_grid_leaves(param_space)
        grid_values = [vals for _, vals in grids]
        combos = list(itertools.product(*grid_values)) if grids else [()]
        for _ in range(num_samples):
            for combo in combos:
                variant = _sample_leaves(param_space, self._rng)
                for (path, _), val in zip(grids, combo):
                    _set_path(variant, path, val)
                yield variant

    # Searcher interface: basic variants don't adapt to results.
    def suggest(self, trial_id: str) -> Optional[Dict]:
        return None

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass
