"""Ax searcher adapter.

Reference: python/ray/tune/search/ax/ax_search.py — an adapter over
Meta's Ax (Adaptive Experimentation) service API. The adapter converts
the tune search space to Ax parameter definitions, pulls suggestions
from an `AxClient`, and reports completions back. Ax is an optional
dependency: importing this module works everywhere; constructing
`AxSearch` without ax installed raises with install guidance (the same
gating the Optuna adapter uses for its dependency).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _to_ax_parameters(space: Dict[str, Any]) -> list:
    params = []
    for name, dom in sorted(space.items()):
        if isinstance(dom, Categorical):
            params.append({"name": name, "type": "choice",
                           "values": list(dom.categories)})
        elif isinstance(dom, Float):
            params.append({"name": name, "type": "range",
                           "bounds": [dom.lower, dom.upper],
                           "value_type": "float",
                           "log_scale": bool(dom.log)})
        elif isinstance(dom, Integer):
            params.append({"name": name, "type": "range",
                           "bounds": [dom.lower, dom.upper - 1],
                           "value_type": "int"})
        else:
            raise ValueError(
                f"AxSearch cannot express domain {dom!r} for {name!r}")
    return params


class AxSearch(Searcher):
    def __init__(self,
                 space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 ax_client=None,
                 **ax_kwargs):
        try:
            from ax.service.ax_client import AxClient
        except ImportError as e:
            raise ImportError(
                "AxSearch requires the 'ax-platform' package "
                "(pip install ax-platform); for a dependency-free "
                "Bayesian searcher use "
                "ray_tpu.tune.search.bayesopt.BayesOptSearch") from e
        super().__init__(metric, mode)
        self._metric = metric
        self._mode = mode
        self._space = dict(space or {})
        self._fixed: Dict[str, Any] = {}
        self._client = ax_client or AxClient(**ax_kwargs)
        self._experiment_created = False
        self._live: Dict[str, int] = {}  # trial_id -> ax trial index

    def set_search_properties(self, metric, mode, config=None) -> None:
        self._metric = metric or self._metric
        self._mode = mode or self._mode
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
            self._fixed = {k: v for k, v in config.items()
                           if not isinstance(v, Domain)}

    def _ensure_experiment(self) -> None:
        if not self._experiment_created:
            self._client.create_experiment(
                parameters=_to_ax_parameters(self._space),
                objectives=None if self._metric is None else {
                    self._metric: __import__(
                        "ax.service.utils.instantiation",
                        fromlist=["ObjectiveProperties"]
                    ).ObjectiveProperties(minimize=self._mode == "min")})
            self._experiment_created = True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        self._ensure_experiment()
        params, index = self._client.get_next_trial()
        self._live[trial_id] = index
        return {**self._fixed, **params}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        index = self._live.pop(trial_id, None)
        if index is None:
            return
        if error or not result or self._metric not in result:
            self._client.abandon_trial(index)
            return
        self._client.complete_trial(
            index, raw_data={self._metric: float(result[self._metric])})
