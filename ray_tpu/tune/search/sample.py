"""Search-space primitives.

Reference: python/ray/tune/search/sample.py (Categorical/Float/Integer
domains and the ``tune.uniform/loguniform/choice/randint/...`` factory
functions) and python/ray/tune/search/variant_generator.py (grid_search
marker dicts). Samplers draw from a numpy Generator so variant generation
is deterministic under a seed.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Sequence

import numpy as np


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(len(self.categories)))]

    def __repr__(self):
        return f"choice({self.categories})"


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: float | None = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = float(rng.uniform(self.lower, self.upper))
        if self.q is not None:
            v = round(v / self.q) * self.q
        return float(v)

    def __repr__(self):
        kind = "loguniform" if self.log else "uniform"
        return f"{kind}({self.lower}, {self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            return int(math.exp(rng.uniform(math.log(self.lower),
                                            math.log(self.upper))))
        return int(rng.integers(self.lower, self.upper))

    def __repr__(self):
        return f"randint({self.lower}, {self.upper})"


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None) if self.fn.__code__.co_argcount else self.fn()


# ---- factory API (parity with ray.tune top-level samplers) ----

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda: float(np.random.normal(mean, sd)))


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    """Marker dict; expanded exhaustively by BasicVariantGenerator."""
    return {"grid_search": list(values)}
