"""Bayesian-optimization searcher — native Gaussian-process UCB.

Reference: python/ray/tune/search/bayesopt/bayesopt_search.py (an adapter
over the `bayes_opt` package). This framework ships a self-contained
implementation instead of an adapter: a small RBF-kernel GP posterior
over the observed (config, score) pairs with an Upper-Confidence-Bound
acquisition maximized over a random candidate pool. No extra
dependencies; numerically robust via jittered Cholesky.

Continuous (Float, incl. log-scale) and Integer dimensions are modeled
in a normalized [0, 1] space; Categorical dimensions are one-hot
embedded. Until `n_initial_points` observations exist, suggestions are
random (space-filling).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class BayesOptSearch(Searcher):
    def __init__(self,
                 space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 n_initial_points: int = 5,
                 kappa: float = 2.0,
                 n_candidates: int = 512,
                 seed: int = 0):
        self._space = dict(space or {})
        self._metric = metric
        self._mode = mode
        self._n_init = n_initial_points
        self._kappa = kappa
        self._n_candidates = n_candidates
        self._rng = np.random.default_rng(seed)
        self._x: List[np.ndarray] = []       # embedded observations
        self._y: List[float] = []            # scores (maximization)
        self._live: Dict[str, np.ndarray] = {}  # trial_id -> embedding

    def set_search_properties(self, metric, mode, config=None) -> None:
        self._metric = metric or self._metric
        self._mode = mode or self._mode
        if config and not self._space:
            self._space = {k: v for k, v in config.items()
                           if isinstance(v, Domain)}
            self._fixed = {k: v for k, v in config.items()
                           if not isinstance(v, Domain)}
        if not getattr(self, "_fixed", None):
            self._fixed = {}

    # ---------------------------------------------------------- embedding
    def _dims(self) -> List[Tuple[str, Domain]]:
        return sorted(self._space.items())

    def _embed_dim(self, dom: Domain, value) -> List[float]:
        if isinstance(dom, Categorical):
            one_hot = [0.0] * len(dom.categories)
            one_hot[dom.categories.index(value)] = 1.0
            return one_hot
        if isinstance(dom, Float):
            lo, hi = dom.lower, dom.upper
            if dom.log:
                return [(math.log(value) - math.log(lo)) /
                        (math.log(hi) - math.log(lo))]
            return [(value - lo) / (hi - lo)]
        if isinstance(dom, Integer):
            return [(value - dom.lower) /
                    max(dom.upper - dom.lower, 1)]
        return [0.0]  # Function/unknown: uninformative

    def _embed(self, config: Dict[str, Any]) -> np.ndarray:
        out: List[float] = []
        for k, dom in self._dims():
            out.extend(self._embed_dim(dom, config[k]))
        return np.asarray(out, np.float64)

    def _random_config(self) -> Dict[str, Any]:
        return {k: dom.sample(self._rng) for k, dom in self._dims()}

    # ---------------------------------------------------------------- GP
    def _gp_posterior(self, cand: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Mean/std at candidate points for a zero-mean RBF GP."""
        x = np.stack(self._x)                      # [n, d]
        y = np.asarray(self._y)
        mu_y, sd_y = y.mean(), max(y.std(), 1e-9)
        yn = (y - mu_y) / sd_y
        ls = 0.25 * math.sqrt(max(x.shape[1], 1))  # length scale
        noise = 1e-4

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / ls ** 2)

        K = k(x, x) + noise * np.eye(len(x))
        L = np.linalg.cholesky(K + 1e-8 * np.eye(len(x)))
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        Ks = k(x, cand)                            # [n, m]
        mean = Ks.T @ alpha
        v = np.linalg.solve(L, Ks)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return mean * sd_y + mu_y, np.sqrt(var) * sd_y

    # ----------------------------------------------------------- Searcher
    def suggest(self, trial_id: str) -> Optional[Dict]:
        if not self._space:
            return dict(getattr(self, "_fixed", {}))
        if len(self._y) < self._n_init:
            config = self._random_config()
        else:
            cands = [self._random_config()
                     for _ in range(self._n_candidates)]
            emb = np.stack([self._embed(c) for c in cands])
            mean, std = self._gp_posterior(emb)
            config = cands[int(np.argmax(mean + self._kappa * std))]
        self._live[trial_id] = self._embed(config)
        return {**getattr(self, "_fixed", {}), **config}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        emb = self._live.pop(trial_id, None)
        if emb is None or error or not result or \
                self._metric not in result:
            return
        score = float(result[self._metric])
        if self._mode == "min":
            score = -score
        self._x.append(emb)
        self._y.append(score)
