"""Trial model.

Reference: python/ray/tune/experiment/trial.py (Trial: id, config, status
lifecycle PENDING→RUNNING→TERMINATED/ERROR, last_result, checkpoints).
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    config: Dict[str, Any]
    experiment_dir: str
    trial_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    checkpoint_path: Optional[str] = None
    num_failures: int = 0
    # Per-trial resource override (ResourceChangingScheduler); None means
    # the controller's experiment-wide trial_resources apply.
    resources: Optional[Dict[str, float]] = None

    @property
    def trial_dir(self) -> str:
        return os.path.join(self.experiment_dir, f"trial_{self.trial_id}")

    def best_metric(self, metric: str, mode: str) -> Optional[float]:
        vals = [r[metric] for r in self.results if metric in r
                and isinstance(r[metric], (int, float))]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"
