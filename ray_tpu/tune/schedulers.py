"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference: python/ray/tune/schedulers/ — async_hyperband.py
(AsyncHyperBandScheduler: rungs at reduction_factor^k, cutoff = top
1/reduction_factor quantile of recorded rung results), median_stopping_rule
.py, pbt.py (PopulationBasedTraining: quantile exploit + perturb/resample
explore via checkpoint transfer). Decisions are returned to the
TuneController which owns actor lifecycle.
"""

from __future__ import annotations

import math
import random
from typing import Callable, Dict, List, Optional

from ray_tpu.tune.search.sample import Domain

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None and metric:
            self.metric = metric
        if getattr(self, "mode", None) is None and mode:
            self.mode = mode

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t, self.grace_period = max_t, grace_period
        self.rf = reduction_factor
        # rung levels: grace_period * rf^k below max_t; {level: [scores]}
        self._rungs: List[Dict] = []
        for b in range(brackets):
            levels = []
            t = grace_period * (self.rf ** b)
            while t < max_t:
                levels.append(int(t))
                t *= self.rf
            self._rungs.append({lv: [] for lv in levels})
        self._trial_bracket: Dict[str, int] = {}

    def _score(self, result: Dict) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        b = self._trial_bracket.setdefault(
            trial.trial_id, len(self._trial_bracket) % len(self._rungs))
        rung = self._rungs[b]
        decision = CONTINUE
        for level in sorted(rung, reverse=True):
            if t < level:
                continue
            recorded = rung[level]
            if trial.trial_id not in [r[0] for r in recorded]:
                recorded.append((trial.trial_id, score))
                k = max(1, int(len(recorded) / self.rf))
                cutoff = sorted((s for _, s in recorded),
                                reverse=True)[k - 1]
                if score < cutoff:
                    decision = STOP
            break
        return decision


# Synchronous HyperBand shares the successive-halving math; the async
# variant dominates it in practice (reference recommends ASHA,
# python/ray/tune/schedulers/async_hyperband.py module docstring).
HyperBandScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of the
    running means of completed/running trials at the same step."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._means: Dict[str, List[float]] = {}

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is None or t < self.grace_period:
            return CONTINUE
        s = float(v) if self.mode == "max" else -float(v)
        hist = self._means.setdefault(trial.trial_id, [])
        hist.append(s)
        means = [sum(h) / len(h) for tid, h in self._means.items() if h]
        if len(means) < self.min_samples:
            return CONTINUE
        median = sorted(means)[len(means) // 2]
        my_mean = sum(hist) / len(hist)
        return STOP if my_mean < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: every perturbation_interval steps, bottom-quantile trials clone
    a top-quantile trial's checkpoint and continue with perturbed
    hyperparameters (reference pbt.py: _exploit, explore factors 1.2/0.8,
    resample_probability 0.25)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 custom_explore_fn: Optional[Callable] = None,
                 seed: int = 0):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.custom_explore_fn = custom_explore_fn
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}

    def _score(self, result: Dict) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def explore(self, config: Dict) -> Dict:
        import numpy as np

        new = dict(config)
        for k, spec in self.mutations.items():
            resample = self._rng.random() < self.resample_p or k not in new
            if isinstance(spec, Domain):
                if resample or not isinstance(new[k], (int, float)):
                    new[k] = spec.sample(np.random.default_rng(
                        self._rng.randrange(2 ** 31)))
                else:  # continuous perturbation ×0.8 / ×1.2
                    factor = self._rng.choice([0.8, 1.2])
                    new[k] = type(new[k])(new[k] * factor)
            elif isinstance(spec, list):
                if resample or new[k] not in spec:
                    new[k] = self._rng.choice(spec)
                else:  # shift to a neighboring value
                    idx = spec.index(new[k]) + self._rng.choice([-1, 1])
                    new[k] = spec[max(0, min(len(spec) - 1, idx))]
            elif callable(spec):
                new[k] = spec()
        if self.custom_explore_fn:
            new = self.custom_explore_fn(new)
        return new

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        score = self._score(result)
        if score is not None:
            self._scores[trial.trial_id] = score
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._scores) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(math.ceil(n * self.quantile)))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and top:
            donor_id = self._rng.choice(top)
            if donor_id != trial.trial_id:
                controller.exploit(trial, donor_id, self.explore)
        return CONTINUE
