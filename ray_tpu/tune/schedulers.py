"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference: python/ray/tune/schedulers/ — async_hyperband.py
(AsyncHyperBandScheduler: rungs at reduction_factor^k, cutoff = top
1/reduction_factor quantile of recorded rung results), median_stopping_rule
.py, pbt.py (PopulationBasedTraining: quantile exploit + perturb/resample
explore via checkpoint transfer). Decisions are returned to the
TuneController which owns actor lifecycle.
"""

from __future__ import annotations

import math
import random
from typing import Callable, Dict, List, Optional

from ray_tpu.tune.search.sample import Domain

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None and metric:
            self.metric = metric
        if getattr(self, "mode", None) is None and mode:
            self.mode = mode

    def _score(self, result: Dict) -> Optional[float]:
        """Internal maximize-normalized metric value."""
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_add(self, controller, trial) -> None:
        pass

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t, self.grace_period = max_t, grace_period
        self.rf = reduction_factor
        # rung levels: grace_period * rf^k below max_t; {level: [scores]}
        self._rungs: List[Dict] = []
        for b in range(brackets):
            levels = []
            t = grace_period * (self.rf ** b)
            while t < max_t:
                levels.append(int(t))
                t *= self.rf
            self._rungs.append({lv: [] for lv in levels})
        self._trial_bracket: Dict[str, int] = {}

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        b = self._trial_bracket.setdefault(
            trial.trial_id, len(self._trial_bracket) % len(self._rungs))
        rung = self._rungs[b]
        decision = CONTINUE
        for level in sorted(rung, reverse=True):
            if t < level:
                continue
            recorded = rung[level]
            if trial.trial_id not in [r[0] for r in recorded]:
                recorded.append((trial.trial_id, score))
                k = max(1, int(len(recorded) / self.rf))
                cutoff = sorted((s for _, s in recorded),
                                reverse=True)[k - 1]
                if score < cutoff:
                    decision = STOP
            break
        return decision


class HyperBandScheduler(TrialScheduler):
    """Bracketed (synchronous-style) successive halving.

    Reference: python/ray/tune/schedulers/hyperband.py — trials fill
    brackets; at each rung boundary the bracket keeps its top
    1/reduction_factor trials. Unlike ASHA (which cuts each trial
    immediately against the current rung quantile), halving decisions
    here wait until every live bracket member reports at the rung, so
    early finishers are never killed against a half-empty rung.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 81, reduction_factor: float = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self.rf = reduction_factor
        # s_max+1 bracket shapes (reference hyperband math): bracket s
        # starts trials at r = max_t / rf^s and halves at each rung.
        # +eps: math.log(243, 3) == 4.9999... must floor to 5, not 4.
        self.s_max = int(math.log(max_t, reduction_factor) + 1e-9)
        self._brackets: List[Dict] = []
        self._trial_bracket: Dict[str, Dict] = {}
        self._next_bracket = 0

    def _new_bracket(self) -> Dict:
        s = self.s_max - (self._next_bracket % (self.s_max + 1))
        self._next_bracket += 1
        r0 = max(1, int(self.max_t / (self.rf ** s)))
        rungs = []
        t = r0
        while t < self.max_t:
            rungs.append(int(t))
            t *= self.rf
        capacity = max(1, int(math.ceil((self.s_max + 1) / (s + 1) *
                                        (self.rf ** s))))
        return {"rungs": rungs, "capacity": capacity, "members": set(),
                "results": {lv: {} for lv in rungs}, "stopped": set()}

    def _bracket_of(self, trial) -> Dict:
        b = self._trial_bracket.get(trial.trial_id)
        if b is None:
            if not self._brackets or \
                    len(self._brackets[-1]["members"]) >= \
                    self._brackets[-1]["capacity"]:
                self._brackets.append(self._new_bracket())
            b = self._brackets[-1]
            b["members"].add(trial.trial_id)
            self._trial_bracket[trial.trial_id] = b
        return b

    def on_trial_add(self, controller, trial) -> None:
        # Join the bracket at START so rung completeness counts every
        # concurrently-running member, not just those that reported.
        self._bracket_of(trial)

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        b = self._bracket_of(trial)
        if trial.trial_id in b["stopped"]:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        for level in sorted(b["rungs"], reverse=True):
            if t < level:
                continue
            b["results"][level].setdefault(trial.trial_id, score)
            live = b["members"] - b["stopped"]
            recorded = {tid: s for tid, s in b["results"][level].items()
                        if tid in live}
            # The bracket may still be filling (max_concurrent below
            # capacity): halving against a partial cohort would kill
            # trials that are top-k of the FULL bracket. Wait until the
            # bracket is full — or no further trials can ever join.
            more_coming = (len(b["members"]) < b["capacity"] and
                           controller is not None and
                           controller.has_pending_trials())
            if len(recorded) >= len(live) and len(recorded) > 1 and \
                    not more_coming:
                # Whole rung reported: halve the bracket.
                keep = max(1, int(len(recorded) / self.rf))
                ranked = sorted(recorded.items(), key=lambda kv: -kv[1])
                for tid, _ in ranked[keep:]:
                    b["stopped"].add(tid)
            break
        return STOP if trial.trial_id in b["stopped"] else CONTINUE

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        b = self._trial_bracket.get(trial.trial_id)
        if b is not None:
            b["stopped"].add(trial.trial_id)


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand variant paired with the TuneBOHB searcher (reference:
    python/ray/tune/schedulers/hb_bohb.py): identical halving; the
    model-based config proposals come from the searcher."""


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of the
    running means of completed/running trials at the same step."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._means: Dict[str, List[float]] = {}

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        s = self._score(result)
        t = result.get(self.time_attr, 0)
        if s is None or t < self.grace_period:
            return CONTINUE
        hist = self._means.setdefault(trial.trial_id, [])
        hist.append(s)
        means = [sum(h) / len(h) for tid, h in self._means.items() if h]
        if len(means) < self.min_samples:
            return CONTINUE
        median = sorted(means)[len(means) // 2]
        my_mean = sum(hist) / len(hist)
        return STOP if my_mean < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: every perturbation_interval steps, bottom-quantile trials clone
    a top-quantile trial's checkpoint and continue with perturbed
    hyperparameters (reference pbt.py: _exploit, explore factors 1.2/0.8,
    resample_probability 0.25)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 custom_explore_fn: Optional[Callable] = None,
                 seed: int = 0):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.custom_explore_fn = custom_explore_fn
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}

    def explore(self, config: Dict) -> Dict:
        import numpy as np

        new = dict(config)
        for k, spec in self.mutations.items():
            resample = self._rng.random() < self.resample_p or k not in new
            if isinstance(spec, Domain):
                if resample or not isinstance(new[k], (int, float)):
                    new[k] = spec.sample(np.random.default_rng(
                        self._rng.randrange(2 ** 31)))
                else:  # continuous perturbation ×0.8 / ×1.2
                    factor = self._rng.choice([0.8, 1.2])
                    new[k] = type(new[k])(new[k] * factor)
            elif isinstance(spec, list):
                if resample or new[k] not in spec:
                    new[k] = self._rng.choice(spec)
                else:  # shift to a neighboring value
                    idx = spec.index(new[k]) + self._rng.choice([-1, 1])
                    new[k] = spec[max(0, min(len(spec) - 1, idx))]
            elif callable(spec):
                new[k] = spec()
        if self.custom_explore_fn:
            new = self.custom_explore_fn(new)
        return new

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        score = self._score(result)
        if score is not None:
            self._record_datapoint(trial, score)
            self._scores[trial.trial_id] = score
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._scores) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(math.ceil(n * self.quantile)))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and top:
            donor_id = self._rng.choice(top)
            if donor_id != trial.trial_id:
                controller.exploit(trial, donor_id, self.explore)
                self._on_exploited(trial)
        return CONTINUE

    def _on_exploited(self, trial) -> None:
        """Hook for model-based variants (PB2)."""

    def _record_datapoint(self, trial, score: float) -> None:
        """Hook for model-based variants (PB2)."""


class DistributeResources:
    """Default allocation policy for ResourceChangingScheduler: divide the
    cluster's CPUs evenly among live trials (reference:
    python/ray/tune/schedulers/resource_changing_scheduler.py
    DistributeResources — bundle-free variant). Never drops a trial below
    its base allocation."""

    def __init__(self, resource: str = "CPU"):
        self.resource = resource

    def __call__(self, controller, trial, result,
                 scheduler) -> Optional[Dict[str, float]]:
        import ray_tpu

        try:
            total = ray_tpu.cluster_resources().get(self.resource, 0.0)
        except Exception:
            return None
        live = max(1, len(controller._actors))
        base = (controller.trial_resources or {}).get(self.resource, 1.0)
        share = max(base, total // live)
        cur = (trial.resources or controller.trial_resources or {}).get(
            self.resource, 1.0)
        if share == cur:
            return None
        new = dict(trial.resources or controller.trial_resources or {})
        new[self.resource] = share
        return new


class ResourceChangingScheduler(TrialScheduler):
    """Wraps a base scheduler and reallocates trial resources while the
    experiment runs (reference: python/ray/tune/schedulers/
    resource_changing_scheduler.py). After the base scheduler's decision,
    ``resources_allocation_function(controller, trial, result, scheduler)``
    may return a new resource dict; a changed allocation checkpoint-pauses
    the trial and restarts its actor with the new resources
    (TuneController.reallocate). User code reads its current allocation
    via ``tune.get_trial_resources()``."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function: Optional[Callable] = None):
        self.base = base_scheduler or FIFOScheduler()
        self.alloc_fn = resources_allocation_function or \
            DistributeResources()

    def set_search_properties(self, metric, mode) -> None:
        super().set_search_properties(metric, mode)
        self.base.set_search_properties(metric, mode)

    def on_trial_add(self, controller, trial) -> None:
        self.base.on_trial_add(controller, trial)

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        decision = self.base.on_trial_result(controller, trial, result)
        if decision == STOP:
            return STOP
        try:
            new = self.alloc_fn(controller, trial, result, self)
        except Exception:
            new = None
        if new and new != (trial.resources or controller.trial_resources):
            controller.reallocate(trial, new)
        return decision

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        self.base.on_trial_complete(controller, trial, result)


class PB2(PopulationBasedTraining):
    """Population Based Bandits: PBT whose explore step picks new
    hyperparameters by a GP-UCB acquisition over observed
    (hyperparams -> score improvement) data, instead of random
    perturbation (reference: python/ray/tune/schedulers/pb2.py; the GP
    here is a plain-numpy RBF regressor — no GPy dependency).

    hyperparam_bounds: {name: (low, high)} continuous ranges.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 seed: int = 0):
        super().__init__(time_attr=time_attr, metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = hyperparam_bounds or {}
        self._prev_score: Dict[str, float] = {}
        self._data: List = []  # (normalized hyperparam vec, score delta)

    def _normalize(self, config: Dict):
        import numpy as np

        vec = []
        for name, (lo, hi) in self.bounds.items():
            v = float(config.get(name, lo))
            vec.append((v - lo) / max(hi - lo, 1e-12))
        return np.asarray(vec)

    def _record_datapoint(self, trial, score: float) -> None:
        prev = self._prev_score.get(trial.trial_id)
        self._prev_score[trial.trial_id] = score
        if prev is None or not self.bounds:
            return
        self._data.append((self._normalize(trial.config), score - prev))
        if len(self._data) > 512:
            self._data.pop(0)

    def _on_exploited(self, trial) -> None:
        # The next score jump comes from the DONOR's checkpoint, not from
        # the new hyperparameters: drop the delta baseline so that jump
        # never enters the GP data.
        self._prev_score.pop(trial.trial_id, None)

    def explore(self, config: Dict) -> Dict:
        """GP-UCB over score improvements (falls back to uniform sampling
        until enough data exists)."""
        import numpy as np

        new = dict(config)
        if not self.bounds:
            return new
        rng = np.random.default_rng(self._rng.randrange(2 ** 31))
        n_cand = 128
        cands = rng.random((n_cand, len(self.bounds)))
        if len(self._data) >= 4:
            X = np.stack([d[0] for d in self._data])
            y = np.asarray([d[1] for d in self._data])
            y = (y - y.mean()) / (y.std() + 1e-9)

            def rbf(a, b, ls=0.2):
                d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
                return np.exp(-d2 / (2 * ls * ls))

            K = rbf(X, X) + 1e-3 * np.eye(len(X))
            Ks = rbf(cands, X)
            Kinv_y = np.linalg.solve(K, y)
            mu = Ks @ Kinv_y
            v = np.linalg.solve(K, Ks.T)
            var = np.clip(1.0 - (Ks * v.T).sum(-1), 1e-9, None)
            ucb = mu + 1.0 * np.sqrt(var)
            best = cands[int(np.argmax(ucb))]
        else:
            best = cands[0]
        for i, (name, (lo, hi)) in enumerate(self.bounds.items()):
            value = lo + float(best[i]) * (hi - lo)
            if isinstance(config.get(name), int):
                value = int(round(value))
            new[name] = value
        if self.custom_explore_fn:
            new = self.custom_explore_fn(new)
        return new
