"""Aim logger, gated on the ``aim`` package.

Reference: python/ray/tune/logger/aim.py:26 (AimLoggerCallback — one
aim.Run per trial, params as run attributes, metrics tracked per
step). The dependency-free local tracker
(ray_tpu.air.integrations.tracking) is the in-tree default.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.tune.logger import LoggerCallback, _flatten


def _import_aim():
    try:
        import aim
    except ImportError as e:
        raise ImportError(
            "aim is not installed (`pip install aim`); or use the "
            "dependency-free in-tree tracker: "
            "ray_tpu.air.integrations.TrackingLoggerCallback") from e
    return aim


class AimLoggerCallback(LoggerCallback):
    """Tune callback: one aim.Run per trial."""

    def __init__(self, repo: Optional[str] = None,
                 experiment: Optional[str] = None,
                 metrics: Optional[List[str]] = None,
                 **run_kwargs):
        super().__init__()
        self._aim = _import_aim()
        self._repo = repo
        self._experiment = experiment
        self._metrics = set(metrics) if metrics else None
        self._run_kwargs = run_kwargs
        self._runs: Dict[str, Any] = {}

    def _run_for(self, trial):
        run = self._runs.get(trial.trial_id)
        if run is None:
            run = self._aim.Run(
                repo=self._repo or trial.experiment_dir,
                experiment=self._experiment, **self._run_kwargs)
            run["trial_id"] = trial.trial_id
            run["hparams"] = {k: v for k, v in
                              _flatten(trial.config).items()}
            self._runs[trial.trial_id] = run
        return run

    def on_trial_start(self, trial) -> None:
        self._run_for(trial)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        run = self._run_for(trial)
        step = result.get("training_iteration")
        for k, v in _flatten(result).items():
            if self._metrics is not None and k not in self._metrics:
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                run.track(v, name=k, step=step)

    def on_trial_complete(self, trial) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.close()

    def on_experiment_end(self, trials: List) -> None:
        for run in self._runs.values():
            run.close()
        self._runs.clear()
