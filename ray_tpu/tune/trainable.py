"""Trainable APIs: class Trainable, function trainables, the trial actor.

Reference: python/ray/tune/trainable/trainable.py:58 (class API —
setup/step/save_checkpoint/load_checkpoint) and
trainable/function_trainable.py (function API driven through a
RunnerThread + result queue, same pattern as the train session
python/ray/train/_internal/session.py:111). Both are executed stepwise:
the controller calls ``train()`` once per iteration, which enables
ASHA early stopping and PBT exploit/explore without cooperation from
user code.
"""

from __future__ import annotations

import inspect
import json
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

# ---------------------------------------------------------------------------
# tune session (function API)

_session_lock = threading.local()


def _get_session():
    return getattr(_session_lock, "session", None)


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report one iteration's metrics (and optionally a checkpoint).

    Works inside both tune function trainables and train loops: if no tune
    session is active, falls through to ray_tpu.train.report.
    """
    sess = _get_session()
    if sess is not None:
        sess.report(metrics, checkpoint)
        return
    from ray_tpu.train._internal import session as train_session

    train_session.report(metrics, checkpoint=checkpoint)


# Set by TrainableActor at construction; read by user code through
# get_trial_resources() (reference: tune.get_trial_resources() — exposes
# the trial's current allocation so ResourceChangingScheduler restarts
# can adapt worker counts mid-experiment).
_trial_resources: Dict[str, float] = {}


def get_trial_resources() -> Dict[str, float]:
    """The resources the current trial's actor was launched with."""
    return dict(_trial_resources)


def get_checkpoint() -> Optional[Checkpoint]:
    sess = _get_session()
    if sess is not None:
        return sess.checkpoint
    from ray_tpu.train._internal import session as train_session

    return train_session.get_checkpoint()


class _FnSession:
    """Thread-side mailbox between the user function and train() calls."""

    def __init__(self, checkpoint: Optional[Checkpoint]):
        self.checkpoint = checkpoint
        self.results: "queue.Queue" = queue.Queue(maxsize=1)
        self.done = threading.Event()
        self.error: Optional[str] = None

    def report(self, metrics, checkpoint):
        self.results.put({"metrics": dict(metrics), "checkpoint": checkpoint})


# ---------------------------------------------------------------------------
# class API


class Trainable:
    """Subclass and implement setup/step (+ save/load_checkpoint)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = config or {}
        self.iteration = 0
        self.setup(self.config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable reconfigured in place (PBT fast
        path; otherwise the controller restarts the actor)."""
        return False

    def cleanup(self) -> None:
        pass

    def train(self) -> Dict[str, Any]:
        result = self.step()
        self.iteration += 1
        return result


class FunctionTrainable(Trainable):
    """Adapts fn(config) + tune.report() to the stepwise interface."""

    _fn: Callable = None  # set by wrap_function subclassing

    def __init__(self, config=None, checkpoint: Optional[Checkpoint] = None):
        self._session = _FnSession(checkpoint)
        self._thread: Optional[threading.Thread] = None
        super().__init__(config)

    def setup(self, config):
        fn = type(self)._fn

        def run():
            _session_lock.session = self._session
            try:
                if len(inspect.signature(fn).parameters) >= 1:
                    fn(dict(config))
                else:
                    fn()
            except BaseException:
                self._session.error = traceback.format_exc()
            finally:
                self._session.done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def step(self):
        while True:
            try:
                item = self._session.results.get(timeout=0.05)
                break
            except queue.Empty:
                if self._session.done.is_set():
                    # drain any result reported between the last get and done
                    try:
                        item = self._session.results.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    if self._session.error:
                        raise RuntimeError(
                            f"trainable failed:\n{self._session.error}")
                    return {"done": True}
        out = dict(item["metrics"])
        out["_tune_checkpoint"] = item["checkpoint"]
        return out

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        # Function trainables checkpoint via report(checkpoint=...).
        return None


def wrap_function(fn: Callable) -> type:
    return type(f"fn_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})


# ---------------------------------------------------------------------------
# trial actor — one per running trial, driven by the TuneController


class TrainableActor:
    """Hosts a Trainable instance inside a ray_tpu actor."""

    def __init__(self, trainable_cls: type, config: Dict[str, Any],
                 trial_dir: str,
                 restore_from: Optional[str] = None,
                 trial_resources: Optional[Dict[str, float]] = None):
        os.makedirs(trial_dir, exist_ok=True)
        self._trial_dir = trial_dir
        self._ckpt_index = 0
        self._latest_checkpoint: Optional[str] = restore_from
        global _trial_resources
        _trial_resources = dict(trial_resources or {})
        restore_ckpt = Checkpoint(restore_from) if restore_from else None
        if issubclass(trainable_cls, FunctionTrainable):
            self._trainable = trainable_cls(config, checkpoint=restore_ckpt)
        else:
            self._trainable = trainable_cls(config)
            if restore_from:
                self._trainable.load_checkpoint(restore_from)
        with open(os.path.join(trial_dir, "params.json"), "w") as f:
            json.dump(config, f, default=str)

    def train(self) -> Dict[str, Any]:
        result = self._trainable.train()
        ckpt = result.pop("_tune_checkpoint", None)
        if ckpt is not None:
            # persist the function-API checkpoint under the trial dir
            d = os.path.join(self._trial_dir,
                             f"checkpoint_{self._ckpt_index:06d}")
            self._ckpt_index += 1
            ckpt.to_directory(d)
            self._latest_checkpoint = d
        result.setdefault("done", False)
        result["training_iteration"] = self._trainable.iteration
        result["timestamp"] = time.time()
        with open(os.path.join(self._trial_dir, "result.json"), "a") as f:
            json.dump({k: v for k, v in result.items()
                       if not k.startswith("_")}, f, default=str)
            f.write("\n")
        return result

    def save(self) -> Optional[str]:
        if isinstance(self._trainable, FunctionTrainable):
            return self._latest_checkpoint
        d = os.path.join(self._trial_dir,
                         f"checkpoint_{self._ckpt_index:06d}")
        self._ckpt_index += 1
        os.makedirs(d, exist_ok=True)
        self._trainable.save_checkpoint(d)
        self._latest_checkpoint = d
        return d

    def latest_checkpoint(self) -> Optional[str]:
        return self._latest_checkpoint

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        ok = self._trainable.reset_config(new_config)
        if ok:
            self._trainable.config = new_config
        return ok

    def stop(self) -> None:
        self._trainable.cleanup()
