"""Trial loggers/callbacks: CSV, JSON, TensorBoard.

Reference: python/ray/tune/logger/ (CSVLoggerCallback,
JsonLoggerCallback, TBXLoggerCallback) — one file set per trial under the
experiment dir, fed from every reported result.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Reference: ray.tune.Callback — controller lifecycle hooks."""

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


class LoggerCallback(Callback):
    def __init__(self):
        self._trial_dirs: Dict[str, str] = {}

    def _dir_for(self, trial) -> str:
        d = self._trial_dirs.get(trial.trial_id)
        if d is None:
            d = getattr(trial, "trial_dir", None) or \
                os.path.join(".", trial.trial_id)
            os.makedirs(d, exist_ok=True)
            self._trial_dirs[trial.trial_id] = d
        return d


class JsonLoggerCallback(LoggerCallback):
    """result.json: one JSON line per reported result."""

    def on_trial_start(self, trial) -> None:
        with open(os.path.join(self._dir_for(trial), "params.json"),
                  "w") as f:
            json.dump(trial.config, f, default=str)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        with open(os.path.join(self._dir_for(trial), "result.json"),
                  "a") as f:
            json.dump(result, f, default=str)
            f.write("\n")


class CSVLoggerCallback(LoggerCallback):
    """progress.csv with a header union-grown on first write."""

    def __init__(self):
        super().__init__()
        self._writers: Dict[str, tuple] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        flat = _flatten(result)
        entry = self._writers.get(trial.trial_id)
        if entry is None:
            path = os.path.join(self._dir_for(trial), "progress.csv")
            f = open(path, "a", newline="")
            writer = csv.DictWriter(f, fieldnames=sorted(flat))
            writer.writeheader()
            entry = self._writers[trial.trial_id] = (f, writer)
        f, writer = entry
        writer.writerow({k: flat.get(k) for k in writer.fieldnames})
        f.flush()

    def on_trial_complete(self, trial) -> None:
        entry = self._writers.pop(trial.trial_id, None)
        if entry:
            entry[0].close()

    def on_experiment_end(self, trials: List) -> None:
        for f, _ in self._writers.values():
            f.close()
        self._writers.clear()


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard events via tensorboardX/torch; no-op if neither is
    importable (hermetic images)."""

    def __init__(self):
        super().__init__()
        self._writers: Dict[str, Any] = {}
        self._available = True

    def _writer_for(self, trial):
        w = self._writers.get(trial.trial_id)
        if w is None and self._available:
            try:
                try:
                    from tensorboardX import SummaryWriter
                except ImportError:
                    from torch.utils.tensorboard import SummaryWriter
            except Exception:
                self._available = False
                return None
            w = SummaryWriter(log_dir=self._dir_for(trial))
            self._writers[trial.trial_id] = w
        return w

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        w = self._writer_for(trial)
        if w is None:
            return
        step = result.get("training_iteration", 0)
        for k, v in _flatten(result).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)
        w.flush()

    def on_trial_complete(self, trial) -> None:
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()

    def on_experiment_end(self, trials: List) -> None:
        for w in self._writers.values():
            w.close()
        self._writers.clear()


DEFAULT_CALLBACKS = [JsonLoggerCallback, CSVLoggerCallback]
