"""ray_tpu.tune — hyperparameter tuning over the actor runtime.

Parity map to the reference (python/ray/tune/):
- Tuner/TuneConfig/ResultGrid      <- tuner.py:44, tune_config.py, result_grid.py
- Trainable (class + function API) <- trainable/trainable.py:58
- TuneController                   <- execution/tune_controller.py:68
- schedulers (ASHA/PBT/median)     <- schedulers/
- search (grid/random/Searcher)    <- search/
"""

from ray_tpu.tune import schedulers, search
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler,
                                     DistributeResources, FIFOScheduler,
                                     HyperBandForBOHB, HyperBandScheduler,
                                     MedianStoppingRule, PB2,
                                     PopulationBasedTraining,
                                     ResourceChangingScheduler,
                                     TrialScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator, ConcurrencyLimiter,
                                 Searcher, choice, grid_search, lograndint,
                                 loguniform, qloguniform, quniform, randint,
                                 randn, sample_from, uniform)
from ray_tpu.tune.trainable import (Trainable, get_checkpoint,
                                    get_trial_resources, report,
                                    wrap_function)
from ray_tpu.tune.tuner import (TuneConfig, Tuner, run, with_parameters,
                                with_resources)

__all__ = [
    "AsyncHyperBandScheduler", "BasicVariantGenerator", "ConcurrencyLimiter",
    "DistributeResources", "FIFOScheduler", "HyperBandForBOHB",
    "HyperBandScheduler", "MedianStoppingRule", "PB2",
    "PopulationBasedTraining", "ResourceChangingScheduler", "ResultGrid",
    "Searcher", "Trainable",
    "TrialScheduler", "TuneConfig", "Tuner", "choice", "get_checkpoint",
    "get_trial_resources",
    "grid_search", "lograndint", "loguniform", "qloguniform", "quniform",
    "randint", "randn", "report", "run", "sample_from", "schedulers",
    "search", "uniform", "with_parameters", "with_resources",
    "wrap_function",
]
