"""Tuner — the public tuning entrypoint.

Reference: python/ray/tune/tuner.py:44 (`Tuner`, `fit` :344) and
tune_config.py (TuneConfig). Accepts a function trainable, a Trainable
subclass, or a ray_tpu.train trainer instance (whose param_space may
override ``train_loop_config``, mirroring base_trainer.py:608's
Trainer↔Tune coupling — inverted here: the Tuner wraps the trainer).
"""

from __future__ import annotations

import dataclasses
import inspect
import os
import uuid
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.air import RunConfig
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import Trainable, report, wrap_function
from ray_tpu.tune.tune_controller import TuneController


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None


def with_resources(trainable, resources: Dict[str, float]):
    """Attach per-trial resource requests (reference tune/trainable/util)."""
    trainable.__ray_tpu_resources__ = dict(resources)
    return trainable


def with_parameters(fn: Callable, **params):
    """Bind large constant objects to a function trainable."""

    def inner(config):
        return fn(config, **params)

    inner.__name__ = getattr(fn, "__name__", "trainable")
    if hasattr(fn, "__ray_tpu_resources__"):
        inner.__ray_tpu_resources__ = fn.__ray_tpu_resources__
    return inner


def _trainer_to_fn(trainer) -> Callable:
    """Wrap a train.*Trainer so each trial re-fits it with the trial's
    config merged into train_loop_config."""
    import copy

    def fit_trial(config):
        t = copy.copy(trainer)
        loop_cfg = dict(t.train_loop_config or {})
        loop_cfg.update(config.get("train_loop_config", config))
        t.train_loop_config = loop_cfg
        if "scaling_config" in config:
            t.scaling_config = config["scaling_config"]
        # Isolate each trial's storage: sharing the trainer's RunConfig
        # name would make concurrent trials resume from (and prune) each
        # other's checkpoints.
        t.run_config = copy.copy(t.run_config)
        t.run_config.name = (f"{t.run_config.name or 'trainer'}"
                             f"_{uuid.uuid4().hex[:8]}")
        result = t.fit()
        if result.error:
            raise result.error
        metrics = dict(result.metrics or {})
        report(metrics, checkpoint=result.checkpoint)

    fit_trial.__name__ = f"fit_{type(trainer).__name__}"
    return fit_trial


class Tuner:
    def __init__(self,
                 trainable: Union[Callable, type, Any],
                 *,
                 param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._resources = getattr(trainable, "__ray_tpu_resources__", None)

        from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

        if isinstance(trainable, DataParallelTrainer):
            # trainer workers hold the real resources; the driver trial is
            # lightweight
            self._resources = self._resources or {"CPU": 0.5}
            trainable = _trainer_to_fn(trainable)
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self._trainable_cls = trainable
        elif callable(trainable):
            self._trainable_cls = wrap_function(trainable)
        else:
            raise TypeError(f"unsupported trainable: {trainable!r}")

    @classmethod
    def restore(cls, path: str, trainable,
                *,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference: python/ray/tune/tuner.py:243 Tuner.restore).

        `path` is the experiment dir a previous fit() used
        (<storage_path>/<name>). Finished trials keep their results;
        unfinished ones resume from their latest checkpoints; no new
        trials are sampled.
        """
        trials = TuneController.load_experiment_state(path)
        run_config = run_config or RunConfig()
        run_config.name = os.path.basename(path.rstrip("/"))
        run_config.storage_path = os.path.dirname(path.rstrip("/"))
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        tuner._restored_trials = trials
        return tuner

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cfg = self._tune_config
        run = self._run_config
        name = run.name or f"tune_{uuid.uuid4().hex[:8]}"
        exp_dir = os.path.join(run.resolved_storage_path(), name)
        failure = run.failure_config
        controller = TuneController(
            self._trainable_cls,
            self._param_space,
            num_samples=cfg.num_samples,
            metric=cfg.metric,
            mode=cfg.mode,
            scheduler=cfg.scheduler,
            search_alg=cfg.search_alg,
            max_concurrent_trials=cfg.max_concurrent_trials,
            experiment_dir=exp_dir,
            stop=getattr(run, "stop", None),
            max_failures=failure.max_failures if failure else 0,
            trial_resources=self._resources,
            callbacks=getattr(run, "callbacks", None),
            restored_trials=getattr(self, "_restored_trials", None))
        trials = controller.run()
        return ResultGrid(trials, cfg.metric, cfg.mode)


def run(trainable, *, config: Optional[Dict] = None, num_samples: int = 1,
        metric: Optional[str] = None, mode: str = "max",
        scheduler: Optional[TrialScheduler] = None,
        search_alg: Optional[Searcher] = None,
        stop: Optional[Dict] = None,
        storage_path: Optional[str] = None,
        name: Optional[str] = None) -> ResultGrid:
    """Legacy ``tune.run`` convenience API (reference tune/tune.py)."""
    rc = RunConfig(name=name, storage_path=storage_path)
    rc.stop = stop  # type: ignore[attr-defined]
    return Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler,
                               search_alg=search_alg),
        run_config=rc,
    ).fit()
