"""Metrics time-series history: a bounded ring of recent samples.

Reference: the Ray dashboard keeps a short history of key series so an
operator can answer "what was this doing 60 seconds ago" without
standing up Prometheus. Here the recorder samples the SERVING stats
plane — TTFT/TPOT percentiles, occupancy, `kv_used_fraction`, queue
depth, sheds, swap bytes — on a configurable cadence into a bounded
buffer, and exposes the window to `dashboard/head.py`
(`/api/v0/metrics_history`) and the status CLI's trend arrows.

Boundedness is the contract: a recorder left running for days holds at
most ``capacity`` samples. Past the window it does not simply drop the
past — when the buffer fills, the OLDEST half is compacted by
averaging adjacent pairs (weighted by how many raw samples each entry
already represents), so the retained span keeps doubling at coarser
resolution while recent samples stay at full cadence: the `ray status`
trade (fresh detail, coarse history) in ~capacity dicts of memory.

Sampling is pull-driven — `sample(values)` with a stats dict, or
`sample_now()` which aggregates over the engines registered in the
serving state API. A cadence guard makes polling idempotent: callers
can hit the endpoint as fast as they like; at most one sample lands
per ``cadence_s``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["MetricsHistory", "DEFAULT_KEYS", "global_history",
           "sample_now", "reset_global_history", "trend_of_points",
           "collect_serving_sample"]

# The operator's SLO-and-pressure shortlist; callers can widen it.
DEFAULT_KEYS = (
    "ttft_s_p50", "ttft_s_p95", "tpot_s_p50", "tpot_s_p95",
    "slot_occupancy", "kv_used_fraction", "queue_depth",
    "requests_shed", "swap_in_bytes", "swap_out_bytes",
    "tokens_out", "requests_inflight", "spec_acceptance_rate",
)


class MetricsHistory:
    """Bounded sample ring with pair-averaging compaction.

    Each retained entry is ``{"t": <clock>, "n": <raw samples
    folded in>, "values": {key: float}}``. ``capacity`` bounds the
    entry count forever; ``compactions`` counts how many times the old
    half was folded. ``clock`` is injectable (the engine/fleet seam) so
    cadence and trend tests advance time explicitly."""

    def __init__(self, *, capacity: int = 512, cadence_s: float = 1.0,
                 keys: Optional[Sequence[str]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 8:
            raise ValueError("capacity must be >= 8")
        if cadence_s < 0:
            raise ValueError("cadence_s must be >= 0")
        self.capacity = capacity
        self.cadence_s = cadence_s
        self.keys = tuple(keys if keys is not None else DEFAULT_KEYS)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: List[Dict[str, Any]] = []
        self._last_t: Optional[float] = None
        self.samples_taken = 0      # raw samples accepted
        self.samples_skipped = 0    # cadence-guard rejections
        self.compactions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def due(self) -> bool:
        """Would an un-forced `sample()` land right now? Callers with
        an EXPENSIVE values collection (`sample_now` walking every
        engine's stats) check this first so a cadence-rejected poll
        costs a clock read, not a stats sweep."""
        with self._lock:
            return self._last_t is None or \
                self._clock() - self._last_t >= self.cadence_s

    # -- recording ---------------------------------------------------------

    def sample(self, values: Dict[str, float],
               force: bool = False) -> bool:
        """Record one sample (restricted to `self.keys`); returns
        whether it landed. Within ``cadence_s`` of the previous sample
        the call is a cheap no-op unless ``force=True`` — so a polling
        endpoint and a serving loop can both call this blindly."""
        now = self._clock()
        with self._lock:
            if not force and self._last_t is not None and \
                    now - self._last_t < self.cadence_s:
                self.samples_skipped += 1
                return False
            self._last_t = now
            self.samples_taken += 1
            self._samples.append({
                "t": now, "n": 1,
                "values": {k: float(values[k]) for k in self.keys
                           if k in values}})
            if len(self._samples) >= self.capacity:
                self._compact_locked()
            return True

    def _compact_locked(self) -> None:
        """Fold the oldest half pairwise: each pair becomes one entry
        at their weighted-mean time/values. Halves the old half's
        entry count, doubling its per-entry span — repeated fills give
        power-of-two resolution tiers, newest at full cadence."""
        half = len(self._samples) // 2
        old, recent = self._samples[:half], self._samples[half:]
        folded: List[Dict[str, Any]] = []
        for i in range(0, len(old) - 1, 2):
            a, b = old[i], old[i + 1]
            na, nb = a["n"], b["n"]
            n = na + nb
            vals: Dict[str, float] = {}
            for k in set(a["values"]) | set(b["values"]):
                va = a["values"].get(k)
                vb = b["values"].get(k)
                if va is None:
                    vals[k] = vb
                elif vb is None:
                    vals[k] = va
                else:
                    vals[k] = (va * na + vb * nb) / n
            folded.append({"t": (a["t"] * na + b["t"] * nb) / n,
                           "n": n, "values": vals})
        if len(old) % 2:
            folded.append(old[-1])
        self._samples = folded + recent
        self.compactions += 1

    # -- queries -----------------------------------------------------------

    def series(self, key: str) -> List[tuple]:
        """[(t, value), ...] oldest-first for one key (entries missing
        the key are skipped)."""
        with self._lock:
            return [(s["t"], s["values"][key]) for s in self._samples
                    if key in s["values"]]

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._samples[-1]) if self._samples else None

    def trend(self, key: str, *, window: int = 8,
              rel_threshold: float = 0.05) -> int:
        """Direction of the recent curve: +1 rising, -1 falling, 0
        flat/unknown — the status CLI's arrow (see
        `trend_of_points`)."""
        return trend_of_points([v for _, v in self.series(key)],
                               window=window,
                               rel_threshold=rel_threshold)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready dump: knobs, bookkeeping counters, and the
        retained samples oldest-first."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "cadence_s": self.cadence_s,
                "keys": list(self.keys),
                "samples_taken": self.samples_taken,
                "samples_skipped": self.samples_skipped,
                "compactions": self.compactions,
                "samples": [
                    {"t": s["t"], "n": s["n"], **s["values"]}
                    for s in self._samples],
            }


def trend_of_points(points: Sequence[float], *, window: int = 8,
                    rel_threshold: float = 0.05) -> int:
    """+1 rising, -1 falling, 0 flat/unknown: mean of the newest
    ``window`` points vs the ``window`` before them; moves smaller
    than ``rel_threshold`` (relative to the older mean, absolute when
    that is 0) count as flat. Shared by `MetricsHistory.trend` and the
    status CLI (which re-derives arrows from an HTTP-fetched
    snapshot)."""
    if len(points) < 2 * window:
        return 0
    new = sum(points[-window:]) / window
    old = sum(points[-2 * window:-window]) / window
    base = abs(old) if old else 1.0
    if new - old > rel_threshold * base:
        return 1
    if old - new > rel_threshold * base:
        return -1
    return 0


# ---------------------------------------------------------------------------
# Process-global recorder over the serving state registry
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global: Optional[MetricsHistory] = None


def global_history(**kwargs) -> MetricsHistory:
    """The process's shared recorder (built on first use; kwargs only
    apply then). The dashboard's /api/v0/metrics_history samples into
    and serves from this instance."""
    global _global
    with _global_lock:
        if _global is None:
            _global = MetricsHistory(**kwargs)
        return _global


def reset_global_history() -> None:
    """Drop the shared recorder (test isolation)."""
    global _global
    with _global_lock:
        _global = None


def collect_serving_sample() -> Dict[str, float]:
    """One fleet-wide stats dict from every engine registered in the
    serving state API: SLO percentiles as maxima (an SLO is judged on
    the worst replica), occupancy as means, queues/sheds/swap bytes as
    sums. Host-side reads only."""
    from ray_tpu.util.state import serving

    engs = serving.engines()
    vals: Dict[str, float] = {
        "queue_depth": 0.0, "requests_shed": 0.0, "tokens_out": 0.0,
        "swap_in_bytes": 0.0, "swap_out_bytes": 0.0,
        "requests_inflight": 0.0,
        "ttft_s_p50": 0.0, "ttft_s_p95": 0.0,
        "tpot_s_p50": 0.0, "tpot_s_p95": 0.0,
        "slot_occupancy": 0.0, "kv_used_fraction": 0.0,
        "spec_acceptance_rate": 0.0,
    }
    sp_prop = sp_acc = 0.0
    for eng in engs:
        s = eng.stats()
        vals["queue_depth"] += s.get("queue_depth", 0.0)
        vals["requests_shed"] += s.get("requests_shed", 0.0)
        vals["tokens_out"] += s.get("tokens_generated",
                                    float(eng.tokens_out))
        vals["swap_in_bytes"] += s.get("swap_in_bytes", 0.0)
        vals["swap_out_bytes"] += s.get("swap_out_bytes", 0.0)
        vals["requests_inflight"] += (
            s.get("queue_depth", 0.0) + s.get("live_slots", 0.0))
        for k in ("ttft_s_p50", "ttft_s_p95",
                  "tpot_s_p50", "tpot_s_p95"):
            vals[k] = max(vals[k], s.get(k, 0.0))
        vals["slot_occupancy"] += s.get("slot_occupancy", 0.0)
        vals["kv_used_fraction"] += s.get("kv_used_fraction", 0.0)
        sp_prop += s.get("spec_proposed", 0.0)
        sp_acc += s.get("spec_accepted", 0.0)
    if engs:
        vals["slot_occupancy"] /= len(engs)
        vals["kv_used_fraction"] /= len(engs)
    # Proposal-weighted across engines: a busy speculative replica's
    # acceptance dominates an idle one's (0.0 when nothing speculates).
    vals["spec_acceptance_rate"] = sp_acc / sp_prop if sp_prop else 0.0
    return vals


def sample_now(force: bool = False) -> bool:
    """Collect one serving sample into the global recorder (cadence
    guard applies unless forced). The dashboard endpoint calls this on
    every hit, making history pull-driven: no background thread, no
    cost when nobody is looking — and a within-cadence hit skips even
    the stats sweep (see `due`), so aggressive polling stays cheap."""
    h = global_history()
    if not force and not h.due():
        h.samples_skipped += 1
        return False
    return h.sample(collect_serving_sample(), force=force)
