"""Profile events — user-annotated spans in the cluster timeline.

Reference: src/ray/core_worker/profile_event.h (ProfileEvent buffered in
TaskEventBuffer) + python `ray.timeline`. Spans recorded inside any task
or actor flush through the same task-event pipeline and appear in
`ray_tpu.util.timeline.timeline()` Chrome traces.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


@contextlib.contextmanager
def profile(name: str, extra: Optional[dict] = None):
    """``with profile("shuffle"):`` — records a span on the timeline."""
    start = time.time()
    try:
        yield
    finally:
        end = time.time()
        from ray_tpu._private.worker import global_worker_or_none

        worker = global_worker_or_none()
        if worker is not None:
            worker.core.record_profile_event(name, start, end,
                                             extra or {})
