"""Serializability debugging.

Reference: python/ray/util/check_serialize.py
(``inspect_serializability`` — when a task argument or captured closure
fails to pickle, walk the object graph and point at the actual
offending members instead of the opaque top-level error).
"""

from __future__ import annotations

import inspect
from typing import Any, Set, Tuple


class FailureTuple:
    """One unserializable leaf: the object, its name, and its parent."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.obj!r}, name={self.name!r})"


def _serializable(obj: Any) -> bool:
    from ray_tpu.core import serialization

    try:
        serialization.serialize(obj).to_bytes()
        return True
    except Exception:
        return False


def _inspect(obj: Any, name: str, parent: Any, depth: int,
             failures: Set[int], found: list, printer,
             visited: Set[int]) -> bool:
    """Returns True if obj serializes. Descends into closures, defaults,
    __dict__ members, and containers of an unserializable obj to find
    leaves. `visited` breaks cycles (a.other=b; b.other=a is exactly the
    kind of object users debug here)."""
    if id(obj) in visited:
        return False  # already being inspected up-stack (cycle)
    if _serializable(obj):
        return True
    visited.add(id(obj))
    printer(f"{'  ' * depth}✗ {name}: "
            f"{type(obj).__name__} is not serializable")
    found_before = len(found)
    # function closures, dragged-in globals, and defaults
    if inspect.isfunction(obj):
        closure = inspect.getclosurevars(obj)
        for src in (closure.nonlocals, closure.globals):
            for sub_name, sub in src.items():
                _inspect(sub, f"{name}.<closure>.{sub_name}", obj,
                         depth + 1, failures, found, printer, visited)
        for i, sub in enumerate(obj.__defaults__ or ()):
            _inspect(sub, f"{name}.<default#{i}>", obj, depth + 1,
                     failures, found, printer, visited)
    # object attributes
    elif hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
        for sub_name, sub in list(obj.__dict__.items()):
            _inspect(sub, f"{name}.{sub_name}", obj, depth + 1,
                     failures, found, printer, visited)
    # containers (dict keys too — a bad KEY is as fatal as a value)
    elif isinstance(obj, (list, tuple, set)):
        for i, sub in enumerate(obj):
            _inspect(sub, f"{name}[{i}]", obj, depth + 1, failures,
                     found, printer, visited)
    elif isinstance(obj, dict):
        for i, k in enumerate(obj):
            _inspect(k, f"{name}.<key#{i}>", obj, depth + 1, failures,
                     found, printer, visited)
        for k, sub in obj.items():
            try:
                label = f"{name}[{k!r}]"
            except Exception:
                label = f"{name}[<key>]"
            _inspect(sub, label, obj, depth + 1, failures, found,
                     printer, visited)
    if len(found) == found_before and id(obj) not in failures:
        # No deeper offender surfaced: THIS object is the leaf (also
        # covers "descended but every child serialized" — e.g. the
        # unpicklability lives in the object itself).
        failures.add(id(obj))
        found.append(FailureTuple(obj, name, parent))
    return False


def inspect_serializability(obj: Any, name: str = "<object>",
                            print_file=None
                            ) -> Tuple[bool, Set[FailureTuple]]:
    """Check `obj` for serializability; on failure print a tree down to
    the offending members and return (ok, failures)."""
    lines = []

    def printer(s):
        lines.append(s)

    found: list = []
    ok = _inspect(obj, name, None, 0, set(), found, printer, set())
    if not ok:
        header = (f"Checking serializability of {name} "
                  f"({type(obj).__name__})")
        text = "\n".join([header] + lines)
        print(text, file=print_file)
    return ok, set(found)
