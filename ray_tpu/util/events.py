"""Structured cluster events — the export-event framework.

Reference: src/ray/util/event.h + src/ray/protobuf/event.proto + the
dashboard event module (python/ray/dashboard/modules/event/): control-
plane components emit severity-labeled structured events (node up/down,
actor restarts, OOM kills, job transitions, spill activity) that
operators read from the dashboard and `ray_tpu list events`.

Emission is fire-and-forget from any process with a GCS connection; the
GCS keeps a bounded ring (events survive the emitting process). Severity
levels mirror the reference's proto enum.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

DEBUG = "DEBUG"
INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"
FATAL = "FATAL"

SEVERITIES = (DEBUG, INFO, WARNING, ERROR, FATAL)


def make_event(source: str, event_type: str, message: str,
               severity: str = INFO,
               metadata: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    if severity not in SEVERITIES:
        raise ValueError(f"severity must be one of {SEVERITIES}")
    return {
        "timestamp": time.time(),
        "severity": severity,
        "source": source,          # gcs | raylet | worker | serve | ...
        "event_type": event_type,  # e.g. NODE_ADDED, ACTOR_RESTARTED
        "message": message,
        "pid": os.getpid(),
        "metadata": metadata or {},
    }


def emit(source: str, event_type: str, message: str,
         severity: str = INFO,
         metadata: Optional[Dict[str, Any]] = None) -> None:
    """Report one event to the GCS (no-op when not connected)."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is None or getattr(w, "core", None) is None:
        return
    try:
        w.gcs_call("report_events", {
            "events": [make_event(source, event_type, message, severity,
                                  metadata)]})
    except Exception:
        logger.debug("event emission failed", exc_info=True)


def list_events(filters=None, limit: int = 1000,
                severity: Optional[str] = None) -> List[Dict[str, Any]]:
    """Query the GCS event ring (newest last). Filters apply over the
    FULL ring before the limit — otherwise matching events older than
    the newest `limit` would be silently dropped."""
    from ray_tpu.util.state import _filter, _gcs

    rows = _gcs("list_events", {"limit": 10_000})
    if severity:
        rows = [r for r in rows if r.get("severity") == severity]
    rows = _filter(rows, filters)
    return rows[-limit:]
