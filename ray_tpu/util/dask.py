"""Dask-on-ray_tpu scheduler.

Reference: python/ray/util/dask/ (`ray_dask_get`: a dask scheduler that
executes each task-graph node as a Ray task, so independent nodes run
in parallel across the cluster and intermediate results live in the
object store instead of the driver).

The dask graph spec is plain data (a dict of key -> computation, where
a computation is a literal, a key, a task tuple `(callable, *args)`, or
a list of computations), so the scheduler is implemented and tested
against raw graphs without importing dask; `enable_dask_on_ray()` wires
it as the default scheduler when dask IS installed.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray", "disable_dask_on_ray"]


def _ishashable(x: Any) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


@ray_tpu.remote
def _exec_node(fn, spec, *flat):
    """One graph node as a cluster task. Args arrive as (spec, flat
    refs): the runtime resolves only TOP-LEVEL ObjectRef arguments, so
    refs nested inside list computations ride `flat` and the spec
    rebuilds the original (possibly nested) argument structure."""
    def dec(s):
        if isinstance(s, list):
            return [dec(e) for e in s]
        tag, v = s
        return flat[v] if tag == "r" else v

    return fn(*[dec(s) for s in spec])


def _pack(args: List[Any]):
    flat: List[Any] = []

    def enc(a):
        if isinstance(a, ray_tpu.ObjectRef):
            flat.append(a)
            return ("r", len(flat) - 1)
        if isinstance(a, list):
            return [enc(e) for e in a]
        return ("l", a)

    return [enc(a) for a in args], flat


def _build(key: Hashable, dsk: Dict, refs: Dict[Hashable, Any],
           building: set) -> Any:
    """Resolve `key` to an ObjectRef (task nodes) or a literal,
    submitting at most once per key."""
    if key in refs:
        return refs[key]
    if key in building:
        raise ValueError(f"cycle detected in dask graph at {key!r}")
    building.add(key)
    refs[key] = _resolve(dsk[key], dsk, refs, building)
    building.discard(key)
    return refs[key]


def _resolve(comp: Any, dsk: Dict, refs: Dict[Hashable, Any],
             building: set) -> Any:
    if _istask(comp):
        fn = comp[0]
        args = [_resolve(a, dsk, refs, building) for a in comp[1:]]
        spec, flat = _pack(args)
        return _exec_node.remote(fn, spec, *flat)
    if _ishashable(comp) and comp in dsk:
        return _build(comp, dsk, refs, building)
    if isinstance(comp, list):
        return [_resolve(c, dsk, refs, building) for c in comp]
    return comp


def ray_dask_get(dsk: Dict, keys: Any, **kwargs) -> Any:
    """Dask scheduler entry point: execute `dsk` on the cluster and
    return the computed values for `keys` (which mirrors dask's
    possibly-nested key lists)."""
    refs: Dict[Hashable, Any] = {}
    building: set = set()

    def materialize(v):
        if isinstance(v, ray_tpu.ObjectRef):
            return ray_tpu.get(v)
        if isinstance(v, list):
            return [materialize(e) for e in v]
        return v

    def out(k):
        if isinstance(k, list):
            return [out(e) for e in k]
        return materialize(_build(k, dsk, refs, building))

    return out(keys)


def enable_dask_on_ray() -> None:
    """Make ray_dask_get dask's default scheduler (requires dask)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires the 'dask' package "
            "(pip install dask); ray_dask_get itself runs raw dask-spec "
            "graphs without it") from e
    dask.config.set(scheduler=ray_dask_get)


def disable_dask_on_ray() -> None:
    try:
        import dask
    except ImportError:
        return
    dask.config.set(scheduler=None)
