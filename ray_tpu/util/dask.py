"""Dask-on-ray_tpu scheduler.

Reference: python/ray/util/dask/ (`ray_dask_get`: a dask scheduler that
executes each task-graph node as a Ray task, so independent nodes run
in parallel across the cluster and intermediate results live in the
object store instead of the driver).

The dask graph spec is plain data (a dict of key -> computation, where
a computation is a literal, a key, a task tuple `(callable, *args)`, or
a list of computations), so the scheduler is implemented and tested
against raw graphs without importing dask; `enable_dask_on_ray()` wires
it as the default scheduler when dask IS installed.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray", "disable_dask_on_ray"]


def _ishashable(x: Any) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


@ray_tpu.remote
def _exec_node(fn, spec, *flat):
    """One graph node as a cluster task. Args arrive as (spec, flat
    refs): the runtime resolves only TOP-LEVEL ObjectRef arguments, so
    refs nested inside list computations ride `flat` and the spec
    rebuilds the original (possibly nested) argument structure."""
    def dec(s):
        if isinstance(s, list):
            return [dec(e) for e in s]
        tag, v = s
        return flat[v] if tag == "r" else v

    return fn(*[dec(s) for s in spec])


def _pack(args: List[Any]):
    flat: List[Any] = []

    def enc(a):
        if isinstance(a, ray_tpu.ObjectRef):
            flat.append(a)
            return ("r", len(flat) - 1)
        if isinstance(a, list):
            return [enc(e) for e in a]
        return ("l", a)

    return [enc(a) for a in args], flat


def _key_deps(comp: Any, dsk: Dict) -> List[Hashable]:
    """Keys of `dsk` referenced by a computation (iterative walk of the
    nested task/list structure — structural nesting is shallow; KEY
    chains, which can be thousands deep, never recurse here)."""
    deps: List[Hashable] = []
    stack = [comp]
    while stack:
        c = stack.pop()
        if _istask(c):
            stack.extend(c[1:])
        elif isinstance(c, list):
            stack.extend(c)
        elif _ishashable(c) and c in dsk:
            deps.append(c)
    return deps


def _toposort(dsk: Dict, wanted: List[Hashable]) -> List[Hashable]:
    """Dependency-first key order for the needed subgraph; raises on
    cycles. Iterative DFS — no Python recursion on key chains."""
    order: List[Hashable] = []
    state: Dict[Hashable, int] = {}  # 1 = visiting, 2 = done
    for root in wanted:
        stack = [(root, False)]
        while stack:
            key, processed = stack.pop()
            if processed:
                state[key] = 2
                order.append(key)
                continue
            st = state.get(key)
            if st == 2:
                continue
            if st == 1:
                raise ValueError(
                    f"cycle detected in dask graph at {key!r}")
            state[key] = 1
            stack.append((key, True))
            for dep in _key_deps(dsk[key], dsk):
                if state.get(dep) != 2:
                    if state.get(dep) == 1:
                        raise ValueError(
                            f"cycle detected in dask graph at {dep!r}")
                    stack.append((dep, False))
    return order


def _resolve(comp: Any, dsk: Dict, refs: Dict[Hashable, Any]) -> Any:
    """Computation -> ObjectRef/literal. Every referenced KEY is already
    in `refs` (topo order); recursion only follows structural nesting."""
    if _istask(comp):
        fn = comp[0]
        args = [_resolve(a, dsk, refs) for a in comp[1:]]
        spec, flat = _pack(args)
        return _exec_node.remote(fn, spec, *flat)
    if _ishashable(comp) and comp in dsk:
        return refs[comp]
    if isinstance(comp, list):
        return [_resolve(c, dsk, refs) for c in comp]
    return comp


def ray_dask_get(dsk: Dict, keys: Any, **kwargs) -> Any:
    """Dask scheduler entry point: execute `dsk` on the cluster and
    return the computed values for `keys` (which mirrors dask's
    possibly-nested key lists)."""
    wanted: List[Hashable] = []

    def collect(k):
        if isinstance(k, list):
            for e in k:
                collect(e)
        else:
            wanted.append(k)

    collect(keys)
    refs: Dict[Hashable, Any] = {}
    for key in _toposort(dsk, wanted):
        refs[key] = _resolve(dsk[key], dsk, refs)

    # One batched get for every output ref, then rebuild the nesting.
    flat_refs: List[Any] = []

    def index(v):
        if isinstance(v, ray_tpu.ObjectRef):
            flat_refs.append(v)
            return ("r", len(flat_refs) - 1)
        if isinstance(v, list):
            return [index(e) for e in v]
        return ("l", v)

    def shape(k):
        if isinstance(k, list):
            return [shape(e) for e in k]
        return index(refs[k])

    spec = shape(keys)
    values = ray_tpu.get(flat_refs) if flat_refs else []

    def rebuild(s):
        if isinstance(s, list):
            return [rebuild(e) for e in s]
        tag, v = s
        return values[v] if tag == "r" else v

    return rebuild(spec)


_prior_scheduler: list = []  # stack of schedulers replaced by enable


def enable_dask_on_ray() -> None:
    """Make ray_dask_get dask's default scheduler (requires dask).
    Remembers the scheduler it replaced so disable restores it."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires the 'dask' package "
            "(pip install dask); ray_dask_get itself runs raw dask-spec "
            "graphs without it") from e
    _prior_scheduler.append(dask.config.get("scheduler", None))
    dask.config.set(scheduler=ray_dask_get)


def disable_dask_on_ray() -> None:
    """Restore the scheduler that enable_dask_on_ray replaced (not a
    blanket None, which would clobber a user-configured scheduler)."""
    try:
        import dask
    except ImportError:
        return
    prior = _prior_scheduler.pop() if _prior_scheduler else None
    dask.config.set(scheduler=prior)
