"""multiprocessing.Pool API over the actor runtime.

Reference: python/ray/util/multiprocessing/pool.py — a drop-in
``Pool`` whose "processes" are actors, so pool workers survive across
``map`` calls (warm imports, initializer state) and can span the whole
cluster rather than one machine. Supported surface: apply/apply_async,
map/map_async, starmap/starmap_async, imap/imap_unordered (chunked),
initializer/initargs, close/terminate/join, context manager.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class _PoolWorker:
    """One pool 'process': runs chunks of calls sequentially."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, func, chunk, star: bool) -> List[Any]:
        if star:
            return [func(*args) for args in chunk]
        return [func(item) for item in chunk]

    def run_one(self, func, args, kwds):
        return func(*args, **(kwds or {}))


class AsyncResult:
    """multiprocessing.pool.AsyncResult subset over ObjectRefs."""

    def __init__(self, refs: List[Any], single: bool,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def finish():
            try:
                chunks = ray_tpu.get(self._refs)
                if single:
                    self._result = chunks[0]
                else:
                    self._result = list(
                        itertools.chain.from_iterable(chunks))
            except BaseException as e:  # surfaced from get()
                self._error = e
                if error_callback is not None:
                    try:
                        error_callback(e)
                    except Exception:
                        pass
            else:
                # Outside the except scope: a buggy SUCCESS callback
                # must not masquerade as a task failure (the results
                # are computed and must stay retrievable).
                if callback is not None:
                    try:
                        callback(self._result)
                    except Exception:
                        pass
            finally:
                self._done.set()

        threading.Thread(target=finish, daemon=True,
                         name="pool-async-result").start()

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        return self._error is None

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            # Stdlib-compatible: multiprocessing.TimeoutError is a
            # ProcessError subclass DISTINCT from builtin TimeoutError;
            # ported `except multiprocessing.TimeoutError` must fire.
            import multiprocessing as _mp

            raise _mp.TimeoutError("result not ready within timeout")
        if self._error is not None:
            raise self._error
        return self._result


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), **_ignored: Any):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            cpus = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(1, int(cpus))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._n = processes
        cls = ray_tpu.remote(_PoolWorker)
        self._workers = [cls.remote(initializer, tuple(initargs))
                         for _ in range(processes)]
        self._rr = 0
        self._closed = False

    # ---- internals ----
    def _next_worker(self):
        if self._closed:
            raise ValueError("Pool not running")
        w = self._workers[self._rr % self._n]
        self._rr += 1
        return w

    def _chunk(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            # multiprocessing's heuristic: ~4 chunks per worker.
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def _submit_chunks(self, func, iterable, chunksize, star):
        chunks, _ = self._chunk(iterable, chunksize)
        return [self._next_worker().run_chunk.remote(func, c, star)
                for c in chunks]

    # ---- apply ----
    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback=None, error_callback=None) -> AsyncResult:
        ref = self._next_worker().run_one.remote(func, tuple(args),
                                                 kwds or {})
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    # ---- map family ----
    def map(self, func, iterable, chunksize: Optional[int] = None):
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize: Optional[int] = None,
                  callback=None, error_callback=None) -> AsyncResult:
        refs = self._submit_chunks(func, iterable, chunksize, star=False)
        return AsyncResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def starmap(self, func, iterable, chunksize: Optional[int] = None):
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable,
                      chunksize: Optional[int] = None,
                      callback=None, error_callback=None) -> AsyncResult:
        refs = self._submit_chunks(func, iterable, chunksize, star=True)
        return AsyncResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def _lazy_chunks(self, iterable: Iterable,
                     chunksize: Optional[int]):
        """Chunk WITHOUT materializing the iterable: imap over an
        infinite/huge generator must stream (stdlib contract)."""
        if chunksize is None:
            chunksize = 1  # stdlib imap default
        it = iter(iterable)
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return
            yield chunk

    def imap(self, func, iterable, chunksize: Optional[int] = None):
        """Ordered lazy iterator: at most ~2 chunks per worker in
        flight; pulls more from the source as results drain."""
        window = self._n * 2
        chunks = self._lazy_chunks(iterable, chunksize)
        inflight: List[Any] = []
        for chunk in chunks:
            inflight.append(self._next_worker().run_chunk.remote(
                func, chunk, False))
            if len(inflight) >= window:
                for item in ray_tpu.get(inflight.pop(0)):
                    yield item
        while inflight:
            for item in ray_tpu.get(inflight.pop(0)):
                yield item

    def imap_unordered(self, func, iterable,
                       chunksize: Optional[int] = None):
        window = self._n * 2
        chunks = self._lazy_chunks(iterable, chunksize)
        inflight: List[Any] = []
        for chunk in chunks:
            inflight.append(self._next_worker().run_chunk.remote(
                func, chunk, False))
            if len(inflight) >= window:
                done, inflight = ray_tpu.wait(inflight, num_returns=1)
                for item in ray_tpu.get(done[0]):
                    yield item
        while inflight:
            done, inflight = ray_tpu.wait(inflight, num_returns=1)
            for item in ray_tpu.get(done[0]):
                yield item

    # ---- lifecycle ----
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def join(self) -> None:
        if not self._closed:
            raise ValueError("join() before close()")
        # Actors are synchronous: outstanding chunks resolve via their
        # refs; nothing further to wait on pool-side.

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
