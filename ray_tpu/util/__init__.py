"""ray_tpu.util — state API, timeline, pools, debugging helpers."""

from typing import List, Optional


def list_named_actors(all_namespaces: bool = False,
                      namespace: Optional[str] = None) -> List:
    """Live named actors (reference: ray.util.list_named_actors).

    Default: the CALLER's namespace. all_namespaces=True returns
    [{name, namespace, actor_id}] dicts for every namespace (reference
    shape); otherwise a list of name strings."""
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    if not all_namespaces and namespace is None:
        namespace = getattr(worker, "namespace", None) or "default"
    rows = worker.gcs_call(
        "list_named_actors",
        {} if all_namespaces else {"namespace": namespace})
    if all_namespaces:
        return rows
    return [r["name"] for r in rows]


def inspect_serializability(obj, name: str = "<object>", print_file=None):
    from ray_tpu.util.check_serialize import inspect_serializability as f

    return f(obj, name, print_file)


__all__ = ["inspect_serializability", "list_named_actors"]
