"""ray_tpu.util — state API, timeline, collective re-exports."""
