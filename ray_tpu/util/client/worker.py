"""Client-mode worker: the driver API over a proxy connection.

Reference: python/ray/util/client/worker.py (client-side stubs whose
ObjectRefs are ids minted by the server). Activated by
ray_tpu.init(address="ray://host:port").
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, List, Optional, Sequence

import cloudpickle

from ray_tpu.core import rpc
from ray_tpu.core import serialization as ser
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.object_ref import ObjectRef


class _NoopRefCounter:
    """Client-side refs are pinned by the proxy session, not locally."""

    def add_local_ref(self, object_id) -> None:
        pass

    def remove_local_ref(self, object_id) -> None:
        pass


class _CoreShim:
    """Minimal `core` surface ObjectRef construction touches."""

    def __init__(self):
        from ray_tpu.core.ids import WorkerID

        # Session token for descriptor-export caching (api.py): a fresh
        # shim per client connection means exports re-register.
        self.worker_id = WorkerID.from_random()

    def register_borrow(self, object_id, owner_address) -> None:
        pass


class ClientWorker:
    """Implements the Worker surface the public API uses (submit_task /
    create_actor / submit_actor_task / get / put / wait / export /
    gcs_call / kill) by forwarding to a ClientProxyServer."""

    mode = "client"
    reference_counter = _NoopRefCounter()

    def __init__(self, host: str, port: int):
        # Per-connection shim: its worker_id doubles as the session token
        # for descriptor-export caching.
        self.core = _CoreShim()
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._conn: Optional[rpc.Connection] = None
        self._conn_err: Optional[BaseException] = None

        def run():
            asyncio.set_event_loop(self._loop)

            async def connect():
                try:
                    self._conn = await rpc.connect(host, port, timeout=10.0,
                                                   name="ray-client")
                except BaseException as e:
                    self._conn_err = e
                finally:
                    self._ready.set()

            self._loop.run_until_complete(connect())
            if self._conn is not None:
                self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="ray-client-io")
        self._thread.start()
        self._ready.wait(15.0)
        if self._conn is None:
            raise ConnectionError(
                f"could not reach ray client proxy at {host}:{port}: "
                f"{self._conn_err}")
        self._exported: dict = {}

    def _call(self, method: str, data: dict, timeout: float = 300.0):
        fut = asyncio.run_coroutine_threadsafe(
            self._conn.call(method, data, timeout=timeout), self._loop)
        return fut.result(timeout + 5.0)

    # ---- Worker surface ----

    def export(self, fn) -> bytes:
        key = self._exported.get(id(fn))
        if key is None:
            r = self._call("cl_export", {"blob": cloudpickle.dumps(fn)})
            key = r["key"]
            self._exported[id(fn)] = key
        return key

    def put(self, value) -> ObjectRef:
        r = self._call("cl_put", {"value": ser.dumps(value)})
        return ObjectRef(ObjectID(r["object_id"]),
                         owner_address=r["owner"] or None)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        blobs = self._call("cl_get", {
            "ids": [r.id.binary() for r in ref_list],
            "owners": [r.owner_address or "" for r in ref_list],
            "timeout": timeout,
        }, timeout=(timeout or 300.0) + 30.0)
        values = [ser.loads(b) for b in blobs]
        return values[0] if single else values

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        by_id = {r.id.binary(): r for r in refs}
        r = self._call("cl_wait", {
            "ids": [x.id.binary() for x in refs],
            "owners": [x.owner_address or "" for x in refs],
            "num_returns": num_returns, "timeout": timeout,
            "fetch_local": fetch_local,
        }, timeout=(timeout or 300.0) + 30.0)
        return ([by_id[i] for i in r["ready"]],
                [by_id[i] for i in r["pending"]])

    def _refs_from(self, pins: List[dict]) -> List[ObjectRef]:
        return [ObjectRef(ObjectID(p["object_id"]),
                          owner_address=p["owner"] or None) for p in pins]

    job_runtime_env = None

    def set_job_runtime_env(self, env) -> None:
        """Client-side job env: packages (local CLIENT paths) upload
        through the proxied KV once; merged into every submission. Also
        published server-side so NESTED tasks inherit it (shared-proxy
        caveat documented on the server handler)."""
        from ray_tpu._private.runtime_env import prepare_runtime_env

        self.job_runtime_env = prepare_runtime_env(env, self.gcs_call)
        self._call("cl_set_job_env",
                   {"env": ser.dumps(self.job_runtime_env)})

    def _merged_opts(self, opts) -> dict:
        if not self.job_runtime_env:
            return opts
        from ray_tpu._private.runtime_env import merge_runtime_envs

        opts = dict(opts)
        opts["runtime_env"] = merge_runtime_envs(
            self.job_runtime_env, opts.get("runtime_env"))
        return opts

    def submit_task(self, descriptor, args, kwargs,
                    opts) -> List[ObjectRef]:
        pins = self._call("cl_submit_task", {
            "key": descriptor, "args": ser.dumps(args),
            "kwargs": ser.dumps(kwargs),
            "opts": ser.dumps(self._merged_opts(opts))})
        return self._refs_from(pins)

    def create_actor(self, descriptor, args, kwargs, opts) -> ActorID:
        r = self._call("cl_create_actor", {
            "key": descriptor, "args": ser.dumps(args),
            "kwargs": ser.dumps(kwargs),
            "opts": ser.dumps(self._merged_opts(opts))})
        return ActorID(r["actor_id"])

    def submit_actor_task(self, actor_id: ActorID, method: str, args,
                          kwargs, opts) -> List[ObjectRef]:
        pins = self._call("cl_submit_actor_task", {
            "actor_id": actor_id.binary(), "method": method,
            "args": ser.dumps(args), "kwargs": ser.dumps(kwargs),
            "opts": ser.dumps(opts)})
        return self._refs_from(pins)

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        self._call("cl_kill_actor", {"actor_id": actor_id.binary(),
                                     "no_restart": no_restart})

    def gcs_call(self, method: str, data=None, timeout: float = 30.0):
        return self._call("cl_gcs_call", {"method": method, "data": data},
                          timeout=timeout)

    def disconnect(self) -> None:
        if self._conn is not None:
            asyncio.run_coroutine_threadsafe(
                self._conn.close(), self._loop).result(5.0)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5.0)
