"""Client proxy server — remote drivers over a thin wire protocol.

Reference: python/ray/util/client/server/ (the Ray Client gRPC proxy:
client-side ObjectRef stubs, server translates to the real core API —
design notes in client/ARCHITECTURE.md). Here the wire is the
framework's own rpc framing; one proxy serves many client sessions, each
session's objects pinned until it disconnects.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.core import rpc
from ray_tpu.core import serialization as ser
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.object_ref import ObjectRef

logger = logging.getLogger(__name__)


class _ClientSession:
    """Per-connection state: refs pinned on behalf of the client."""

    def __init__(self):
        self.refs: Dict[bytes, ObjectRef] = {}

    def pin(self, ref: ObjectRef) -> dict:
        self.refs[ref.id.binary()] = ref
        return {"object_id": ref.id.binary(),
                "owner": ref.owner_address or ""}

    def resolve(self, object_id: bytes, owner: str) -> ObjectRef:
        ref = self.refs.get(object_id)
        if ref is not None:
            return ref
        return ObjectRef(ObjectID(object_id), owner_address=owner or None)


class ClientProxyHandler:
    """rpc handler; methods run on the proxy's own event loop and offload
    the (sync, thread-safe) driver API to an executor."""

    def __init__(self):
        self.sessions: Dict[Any, _ClientSession] = {}

    def _session(self, conn) -> _ClientSession:
        sess = self.sessions.get(conn)
        if sess is None:
            sess = self.sessions[conn] = _ClientSession()
            prev = conn.on_close
            def _cleanup(c, _prev=prev):
                self.sessions.pop(c, None)
                if _prev:
                    _prev(c)
            conn.on_close = _cleanup
        return sess

    async def _offload(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    # ---- handlers ----

    async def handle_cl_ping(self, data, conn) -> str:
        return "pong"

    async def handle_cl_put(self, data, conn) -> dict:
        import ray_tpu

        sess = self._session(conn)
        value = ser.loads(data["value"])
        ref = await self._offload(ray_tpu.put, value)
        return sess.pin(ref)

    async def handle_cl_get(self, data, conn):
        import ray_tpu
        from ray_tpu._private.worker import global_worker

        sess = self._session(conn)
        refs = [sess.resolve(oid, owner)
                for oid, owner in zip(data["ids"], data["owners"])]
        timeout = data.get("timeout")
        # get() with a LIST argument always returns a list.
        values = await self._offload(
            lambda: global_worker().get(refs, timeout=timeout))
        return [ser.dumps(v) for v in values]

    async def handle_cl_wait(self, data, conn) -> dict:
        from ray_tpu._private.worker import global_worker

        sess = self._session(conn)
        refs = [sess.resolve(oid, owner)
                for oid, owner in zip(data["ids"], data["owners"])]
        ready, pending = await self._offload(
            lambda: global_worker().wait(
                refs, data.get("num_returns", 1), data.get("timeout"),
                data.get("fetch_local", True)))
        return {"ready": [r.id.binary() for r in ready],
                "pending": [r.id.binary() for r in pending]}

    async def handle_cl_export(self, data, conn) -> dict:
        from ray_tpu._private.worker import global_worker

        import cloudpickle

        fn = cloudpickle.loads(data["blob"])
        descriptor = await self._offload(global_worker().export, fn)
        key = descriptor.function_key if hasattr(
            descriptor, "function_key") else descriptor
        self._session(conn).refs  # touch session
        self._descriptors = getattr(self, "_descriptors", {})
        self._descriptors[key] = descriptor
        return {"key": key}

    def _descriptor(self, key):
        return self._descriptors[key]

    async def handle_cl_submit_task(self, data, conn) -> list:
        from ray_tpu._private.worker import global_worker

        sess = self._session(conn)
        args = ser.loads(data["args"])
        kwargs = ser.loads(data["kwargs"])
        opts = ser.loads(data["opts"])
        refs = await self._offload(
            lambda: global_worker().submit_task(
                self._descriptor(data["key"]), args, kwargs, opts))
        return [sess.pin(r) for r in refs]

    async def handle_cl_create_actor(self, data, conn) -> dict:
        from ray_tpu._private.worker import global_worker

        args = ser.loads(data["args"])
        kwargs = ser.loads(data["kwargs"])
        opts = ser.loads(data["opts"])
        actor_id = await self._offload(
            lambda: global_worker().create_actor(
                self._descriptor(data["key"]), args, kwargs, opts))
        return {"actor_id": actor_id.binary()}

    async def handle_cl_submit_actor_task(self, data, conn) -> list:
        from ray_tpu._private.worker import global_worker

        sess = self._session(conn)
        args = ser.loads(data["args"])
        kwargs = ser.loads(data["kwargs"])
        opts = ser.loads(data["opts"])
        refs = await self._offload(
            lambda: global_worker().submit_actor_task(
                ActorID(data["actor_id"]), data["method"], args, kwargs,
                opts))
        return [sess.pin(r) for r in refs]

    async def handle_cl_kill_actor(self, data, conn) -> bool:
        import ray_tpu
        from ray_tpu.core.actor import ActorHandle

        handle = ActorHandle(ActorID(data["actor_id"]))
        await self._offload(
            lambda: ray_tpu.kill(handle,
                                 no_restart=data.get("no_restart", True)))
        return True

    async def handle_cl_set_job_env(self, data, conn) -> bool:
        """Publish the client's job env under the proxy driver's job id
        so NESTED tasks inherit it (note: the proxy driver is shared —
        one job env per proxy process, last writer wins)."""
        from ray_tpu._private.worker import global_worker

        env = ser.loads(data["env"])
        await self._offload(global_worker().set_job_runtime_env, env)
        return True

    async def handle_cl_gcs_call(self, data, conn):
        from ray_tpu._private.worker import global_worker

        return await self._offload(
            lambda: global_worker().gcs_call(data["method"],
                                             data.get("data")))


class ClientProxyServer:
    """Hosts the proxy on its own thread/loop beside a connected driver."""

    def __init__(self, host: str = "127.0.0.1", port: int = 10001):
        self.host = host
        self.port = port
        self._thread: Optional[threading.Thread] = None
        self._loop = None
        self._started = threading.Event()
        self._stop_evt: Optional[asyncio.Event] = None
        self._error: Optional[BaseException] = None

    async def _serve(self) -> None:
        server = rpc.Server(ClientProxyHandler(), self.host, self.port)
        self.port = await server.start()
        self._stop_evt = asyncio.Event()
        self._started.set()
        logger.info("client proxy on %s:%d", self.host, self.port)
        await self._stop_evt.wait()
        await server.close()

    def start(self) -> "ClientProxyServer":
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(self._serve())
            except BaseException as e:
                self._error = e
                self._started.set()
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="client-proxy")
        self._thread.start()
        self._started.wait(10.0)
        if self._error is not None:
            raise RuntimeError(
                f"client proxy failed to start: {self._error}"
            ) from self._error
        return self

    def stop(self) -> None:
        if self._loop and self._stop_evt:
            self._loop.call_soon_threadsafe(self._stop_evt.set)
        if self._thread:
            self._thread.join(timeout=5.0)
