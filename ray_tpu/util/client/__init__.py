"""ray_tpu.util.client — remote-driver (Ray Client) proxy mode.

Parity target: python/ray/util/client/ (gRPC proxy; ARCHITECTURE.md).
Connect with ray_tpu.init(address="ray://host:port"); host a proxy with
ClientProxyServer (or `start --head --client-server-port N`).
"""

from ray_tpu.util.client.server import ClientProxyServer
from ray_tpu.util.client.worker import ClientWorker

__all__ = ["ClientProxyServer", "ClientWorker"]
