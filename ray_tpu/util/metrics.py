"""User-facing metrics API: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py (same three classes, same
tag_keys/default-tags shape) over the native stats registry
(src/ray/stats/). Metrics recorded in any worker flow to the GCS and are
exposed as Prometheus text by the dashboard (/metrics).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import metrics as _impl


def snapshots() -> List[Dict[str, Any]]:
    """Snapshot every metric series registered IN THIS PROCESS — rows
    of ``{name, kind, description, tags, value}`` (histograms add
    ``boundaries/bucket_counts/sum/count``). This is the local view;
    the dashboard's /metrics aggregates the same rows cluster-wide via
    the GCS pusher."""
    return _impl.snapshots()


def prometheus_text(rows: Optional[List[Dict[str, Any]]] = None,
                    prefix: str = "ray_tpu_") -> str:
    """Prometheus text exposition of metric snapshot rows (this
    process's registry by default) — scrape-ready: HELP/TYPE headers,
    escaped sorted labels, cumulative histogram buckets. The engine and
    fleet gauges (`llm.engine.*` / `llm.fleet.*`) come out as
    `ray_tpu_llm_engine_*` / `ray_tpu_llm_fleet_*` series."""
    return _impl.prometheus_text(rows, prefix=prefix)


def reset_registry() -> None:
    """TEST HELPER: clear this process's metric registry so series
    recorded by one test module cannot leak ordering or values into
    another's `snapshots()` / `prometheus_text()` assertions. Existing
    Counter/Gauge/Histogram objects keep working — the backing series
    is lazily re-registered on their next record."""
    _impl.reset_registry()


class _Base:
    _kind = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        _impl.ensure_pusher()

    def set_default_tags(self, tags: Dict[str, str]):
        bad = set(tags) - set(self._tag_keys)
        if bad:
            raise ValueError(f"tags {sorted(bad)} not in tag_keys")
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            bad = set(tags) - set(self._tag_keys)
            if bad:
                raise ValueError(f"tags {sorted(bad)} not in tag_keys")
            merged.update(tags)
        return merged

    @property
    def info(self) -> Dict[str, object]:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}


class Counter(_Base):
    _kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value <= 0:
            raise ValueError("Counter.inc value must be positive")
        m = _impl.register(self._name, "counter", self._description,
                           self._merged(tags))
        _impl.record(m, value, "counter")


class Gauge(_Base):
    _kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        m = _impl.register(self._name, "gauge", self._description,
                           self._merged(tags))
        _impl.record(m, value, "gauge")


class Histogram(_Base):
    _kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = list(
            boundaries or _impl.DEFAULT_HISTOGRAM_BOUNDARIES)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        m = _impl.register(self._name, "histogram", self._description,
                           self._merged(tags), self._boundaries)
        _impl.record(m, value, "histogram")
