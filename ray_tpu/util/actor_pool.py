"""ActorPool — round-robin work distribution over a fixed actor set.

Reference: python/ray/util/actor_pool.py (submit/get_next/
get_next_unordered/map/map_unordered over idle actors)."""

from __future__ import annotations

from typing import Any, Callable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []  # FIFO of refs (ordered mode)

    def has_free(self) -> bool:
        return bool(self._idle)

    def has_next(self) -> bool:
        return bool(self._pending)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn maps (actor, value) -> ObjectRef."""
        if not self._idle:
            # Wait for any in-flight result to free an actor.
            ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                    num_returns=1)
            self._return_actor(ready[0])
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._pending.append(ref)

    def _return_actor(self, ref) -> None:
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in SUBMISSION order. On timeout the result stays
        pending and retrievable by a later call."""
        if not self._pending:
            raise StopIteration("no pending results")
        ref = self._pending[0]
        value = ray_tpu.get(ref, timeout=timeout)  # raises -> ref kept
        self._pending.pop(0)
        self._return_actor(ref)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next COMPLETED result (any order)."""
        if not self._pending:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(self._pending, num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        self._pending.remove(ref)
        value = ray_tpu.get(ref)
        self._return_actor(ref)
        return value

    def map(self, fn: Callable[[Any, Any], Any],
            values: List[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: List[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
