"""Chrome-trace timeline export from GCS task events.

Reference: python/ray/_private/profiling.py:84 (`ray timeline` dumps a
chrome://tracing JSON of task state transitions stored in GcsTaskManager).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Returns chrome-trace events; optionally writes them to filename."""
    from ray_tpu._private.worker import global_worker

    events = global_worker().gcs_call("list_task_events",
                                      {"limit": 100_000}) or []
    events = sorted(events, key=lambda e: e.get("time", 0.0))
    # Pair RUNNING -> FINISHED/FAILED per task into complete ("X") events.
    running: Dict[str, dict] = {}
    trace: List[Dict[str, Any]] = []
    for ev in events:
        tid = ev["task_id"]
        tid = tid.hex() if isinstance(tid, bytes) else str(tid)
        state = ev.get("state")
        if state == "PROFILE":
            worker = ev.get("worker_id", b"")
            worker = worker.hex() if isinstance(worker, bytes) else worker
            trace.append({
                "name": ev.get("name", "span"),
                "cat": "profile",
                "ph": "X",
                "ts": ev["time"] * 1e6,
                "dur": (ev.get("end_time", ev["time"]) - ev["time"]) * 1e6,
                "pid": worker[:8],
                "tid": worker[:8],
                "args": ev.get("extra", {}),
            })
        elif state == "RUNNING":
            running[tid] = ev
        elif state in ("FINISHED", "FAILED") and tid in running:
            start = running.pop(tid)
            worker = start.get("worker_id", b"")
            worker = worker.hex() if isinstance(worker, bytes) else worker
            trace.append({
                "name": start.get("name", "task"),
                "cat": "task",
                "ph": "X",
                "ts": start["time"] * 1e6,
                "dur": (ev["time"] - start["time"]) * 1e6,
                "pid": worker[:8],
                "tid": worker[:8],
                # Distributed trace context (tracing_helper.py:326
                # analog): nested calls share trace_id; parent_span_id
                # is the submitting task. chrome://tracing shows these
                # in the args pane; exporters can rebuild span trees.
                "args": {"task_id": tid, "end_state": state,
                         "trace_id": start.get("trace_id", ""),
                         "parent_span_id": start.get("parent_span_id",
                                                     "")},
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
