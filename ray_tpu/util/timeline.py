"""Chrome-trace timeline export from GCS task events.

Reference: python/ray/_private/profiling.py:84 (`ray timeline` dumps a
chrome://tracing JSON of task state transitions stored in GcsTaskManager).

`chrome_complete_event` is the one event shape every exporter in the
tree shares — the GCS task timeline here and the serving tracer
(`models/engine_trace.py` dump_trace) both emit through it, so a fleet
trace and a task timeline concatenate into one loadable file.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def chrome_complete_event(name: str, cat: str, start_s: float,
                          dur_s: float, pid: Any, tid: Any,
                          args: Optional[dict] = None) -> Dict[str, Any]:
    """One chrome://tracing complete ("X") event. Times are SECONDS in,
    microseconds out (the trace viewer's unit)."""
    return {
        "name": name,
        "cat": cat,
        "ph": "X",
        "ts": start_s * 1e6,
        "dur": max(0.0, dur_s) * 1e6,
        "pid": pid,
        "tid": tid,
        "args": args or {},
    }


def events_to_trace(events: List[dict],
                    now: Optional[float] = None) -> List[Dict[str, Any]]:
    """Pure pairing logic: GCS task events -> chrome-trace events.

    RUNNING -> FINISHED/FAILED pairs become complete ("X") spans;
    PROFILE events pass through directly. A RUNNING event that never
    reached a terminal state is NOT dropped: it becomes an open span
    stretching to `now` (default: the latest timestamp in the feed)
    with ``end_state: "RUNNING"`` in its args — hung work shows up in
    the trace instead of vanishing from it."""
    events = sorted(events, key=lambda e: e.get("time", 0.0))
    running: Dict[str, dict] = {}
    trace: List[Dict[str, Any]] = []
    if now is None:
        now = max((e.get("time", 0.0) for e in events), default=0.0)
        now = max(now, max((e.get("end_time", 0.0) for e in events),
                           default=0.0))
    for ev in events:
        tid = ev["task_id"]
        tid = tid.hex() if isinstance(tid, bytes) else str(tid)
        state = ev.get("state")
        if state == "PROFILE":
            worker = ev.get("worker_id", b"")
            worker = worker.hex() if isinstance(worker, bytes) else worker
            trace.append(chrome_complete_event(
                ev.get("name", "span"), "profile", ev["time"],
                ev.get("end_time", ev["time"]) - ev["time"],
                worker[:8], worker[:8], ev.get("extra", {})))
        elif state == "RUNNING":
            running[tid] = ev
        elif state in ("FINISHED", "FAILED") and tid in running:
            start = running.pop(tid)
            worker = start.get("worker_id", b"")
            worker = worker.hex() if isinstance(worker, bytes) else worker
            trace.append(chrome_complete_event(
                start.get("name", "task"), "task", start["time"],
                ev["time"] - start["time"], worker[:8], worker[:8],
                # Distributed trace context (tracing_helper.py:326
                # analog): nested calls share trace_id; parent_span_id
                # is the submitting task. chrome://tracing shows these
                # in the args pane; exporters can rebuild span trees.
                {"task_id": tid, "end_state": state,
                 "trace_id": start.get("trace_id", ""),
                 "parent_span_id": start.get("parent_span_id", "")}))
    for tid, start in running.items():
        worker = start.get("worker_id", b"")
        worker = worker.hex() if isinstance(worker, bytes) else worker
        trace.append(chrome_complete_event(
            start.get("name", "task"), "task", start["time"],
            now - start["time"], worker[:8], worker[:8],
            {"task_id": tid, "end_state": "RUNNING",
             "trace_id": start.get("trace_id", ""),
             "parent_span_id": start.get("parent_span_id", "")}))
    return trace


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Returns chrome-trace events; optionally writes them to filename."""
    from ray_tpu._private.worker import global_worker

    events = global_worker().gcs_call("list_task_events",
                                      {"limit": 100_000}) or []
    trace = events_to_trace(events)
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
