"""Distributed FIFO queue (reference: python/ray/util/queue.py — an
actor-backed Queue with optional maxsize and blocking put/get)."""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._items: List[Any] = []

    def qsize(self) -> int:
        return len(self._items)

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self._items) >= self.maxsize:
            return False
        self._items.append(item)
        return True

    def get(self):
        if not self._items:
            return False, None
        return True, self._items.pop(0)

    def put_batch(self, items: List[Any]) -> bool:
        """All-or-nothing (matching the reference contract): either the
        whole batch fits or nothing is enqueued."""
        if self.maxsize > 0 and \
                len(self._items) + len(items) > self.maxsize:
            return False
        self._items.extend(items)
        return True

    def get_batch(self, n: int):
        """All-or-nothing: n items or nothing."""
        if len(self._items) < n:
            return None
        out = self._items[:n]
        del self._items[:n]
        return out


class Queue:
    """FIFO queue shared across tasks/actors via one queue actor."""

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        cls = ray_tpu.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self._actor = cls.remote(maxsize)
        self.maxsize = maxsize

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.002
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full("queue is full")
            if deadline is not None and time.monotonic() > deadline:
                raise Full("queue is full (timeout)")
            time.sleep(delay)
            delay = min(delay * 2, 0.1)  # backoff: idle waiters must not
            #                              hammer the queue actor

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.002
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty("queue is empty")
            if deadline is not None and time.monotonic() > deadline:
                raise Empty("queue is empty (timeout)")
            time.sleep(delay)
            delay = min(delay * 2, 0.1)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self._actor.put_batch.remote(list(items))):
            raise Full(f"batch of {len(items)} does not fit")

    def get_nowait_batch(self, n: int) -> List[Any]:
        out = ray_tpu.get(self._actor.get_batch.remote(n))
        if out is None:
            raise Empty(f"fewer than {n} items available")
        return out

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)
