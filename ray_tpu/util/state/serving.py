"""Serving state API — live introspection over engines and fleets.

The cluster half of `ray_tpu.util.state` answers "what are the tasks
and actors doing" from GCS tables; this module is the SERVING-plane
counterpart (reference: `ray status` + the state API over serve
deployments): `DecodeEngine`, `LLMFleet` and `LLMFleetServer` register
themselves WEAKLY at construction, and the query functions snapshot
plain dicts from their live host-side bookkeeping — scheduler queue,
slot table, chunked-prefill frontiers, swap ledger, block-pool
refcounts, prefix-trie occupancy.

Snapshots are read-only by construction: nothing here calls `step()`,
touches a trie's LRU recency, publishes a gauge, or launches a device
program — the same discipline as the router's load probes
(`pending_prefill_tokens` / `kv_used_fraction`). Registration is a
`WeakValueDictionary`, so an engine that goes out of scope disappears
from the listings without an unregister call.

Request phases (`list_requests(status=...)`):

- ``queued``      in the scheduler, no slot yet
- ``prefilling``  bound to a row whose prompt suffix is still being
                  written (chunked prefill frontier mid-prompt)
- ``decoding``    bound to a live row with final logits (emitting)
- ``swapped``     preempted out of the pool, spilled state waiting to
                  swap back in (the request is also re-queued; the
                  swap ledger takes precedence here)
- ``handoff``     moving between replica classes in a disaggregated
                  fleet: prefill finished and the KV is being exported
                  (parked on a prefill-class engine), parked host-side
                  on the fleet (no decode replica importable yet —
                  ``engine_id`` is None), or imported on a decode-class
                  engine and awaiting its decode admission. Handoff
                  WINS over ``swapped``: an imported request also sits
                  in the importer's swap ledger, and counting it twice
                  would double the in-flight census
- ``recovering``  parked in a fleet's retry queue after its replica
                  failed: reconstructed host-side, waiting out its
                  backoff before resubmission (these rows live on the
                  FLEET, not any engine — their ``engine_id`` is None)
- ``draining``    not a phase but a FILTER: any request, in any phase,
                  living on an engine that has begun draining
"""

from __future__ import annotations

import itertools
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "register_engine", "register_fleet", "register_server",
    "engines", "fleets", "servers", "reset_serving_state",
    "engine_state", "engine_requests",
    "list_engines", "list_requests", "list_kv_pools",
    "summarize_fleet",
]

_lock = threading.Lock()
_seq = itertools.count()
_engines: "weakref.WeakValueDictionary[int, Any]" = \
    weakref.WeakValueDictionary()
_fleets: "weakref.WeakValueDictionary[int, Any]" = \
    weakref.WeakValueDictionary()
_servers: "weakref.WeakValueDictionary[int, Any]" = \
    weakref.WeakValueDictionary()


def _register(table, obj) -> None:
    with _lock:
        table[next(_seq)] = obj


def register_engine(engine) -> None:
    """Called by DecodeEngine.__init__ — weak, so no lifecycle hook is
    needed on the engine side."""
    _register(_engines, engine)


def register_fleet(fleet) -> None:
    _register(_fleets, fleet)


def register_server(server) -> None:
    _register(_servers, server)


def _live(table) -> List[Any]:
    with _lock:
        return [obj for _, obj in sorted(table.items())]


def engines() -> List[Any]:
    """Live registered DecodeEngines, registration order."""
    return _live(_engines)


def fleets() -> List[Any]:
    return _live(_fleets)


def servers() -> List[Any]:
    return _live(_servers)


def reset_serving_state() -> None:
    """Drop every registration (test isolation helper — live objects
    keep working, they just stop being listed)."""
    with _lock:
        _engines.clear()
        _fleets.clear()
        _servers.clear()


# ---------------------------------------------------------------------------
# Per-engine snapshots
# ---------------------------------------------------------------------------

def _fleet_of(engine) -> Dict[str, Optional[str]]:
    """(fleet_id, replica name, health state) owning `engine`, by
    identity walk over registered fleets — engines carry no
    back-pointer on purpose (the models layer stays fleet-blind).
    `health` is the fleet's replica lifecycle state (RUNNING /
    SUSPECT / DRAINING / ...); a loose engine reports None."""
    for fleet in fleets():
        for rep in getattr(fleet, "replicas", []):
            if rep.engine is engine:
                return {"fleet": fleet.fleet_id, "replica": rep.name,
                        "health": rep.state,
                        "replica_class": getattr(
                            rep, "replica_class", None)}
    return {"fleet": None, "replica": None, "health": None,
            "replica_class": None}


def engine_state(engine) -> Dict[str, Any]:
    """One engine's row: identity, topology, and the instantaneous
    occupancy/queue/KV numbers the status CLI draws bars from. Pure
    host reads — no step, no device sync, no gauge writes."""
    live = sum(r is not None for r in engine.row_req)
    row = {
        "engine_id": engine.engine_id,
        "batch_slots": engine.B,
        "max_len": engine.max_len,
        "tp_degree": engine.tp_degree,
        "paged": bool(engine.paged),
        "draining": bool(engine.draining),
        "scheduler": type(engine.scheduler).__name__,
        "queue_depth": len(engine.scheduler),
        "live_slots": live,
        "slot_occupancy": live / engine.B,
        "prefilling_rows": len(engine._row_prefill),
        "kv_used_fraction": engine.kv_used_fraction(),
        "kv_free_blocks": engine.kv_free_blocks(),
        "pending_prefill_tokens": engine.pending_prefill_tokens(),
        "requests_swapped": len(engine._swapped) if engine.paged else 0,
        "pipeline_inflight": len(engine._ring),
        "tokens_out": engine.tokens_out,
        "uptime_s": max(0.0, engine._clock() - engine._start_t),
        "steps_total": engine.steps_total,
        # Speculative plane (host counters; all-zero without a draft).
        "spec_enabled": bool(engine.spec_enabled),
        "spec_window": engine.spec_window if engine.spec_enabled else 0,
        "spec_dispatches": engine.spec_dispatches,
        "spec_acceptance_rate": (
            engine.spec_accepted / engine.spec_proposed
            if engine.spec_proposed else 0.0),
    }
    row.update(_fleet_of(engine))
    return row


def _req_row(engine, req, status: str, *, row: Optional[int] = None,
             prefill_pos: Optional[int] = None,
             now: Optional[float] = None) -> Dict[str, Any]:
    entry = {
        "req_id": req.req_id,
        "engine_id": engine.engine_id,
        "status": status,
        "row": row,
        "prompt_tokens": len(req.prompt),
        "max_new_tokens": req.max_new_tokens,
        "tokens_out": len(req.tokens),
        "priority": req.priority,
        "deadline": req.deadline,
        "resume": bool(req.resume),
        "engine_draining": bool(engine.draining),
    }
    if prefill_pos is not None:
        entry["prefill_pos"] = prefill_pos
    # Age rides on EngineMetrics' per-request submit timestamp when the
    # engine keeps one (enable_metrics=False engines report None).
    times = getattr(engine.metrics, "_req", {}).get(req.req_id)
    if times is not None and now is not None:
        entry["age_s"] = max(0.0, now - times.submit_t)
    else:
        entry["age_s"] = None
    return entry


def engine_requests(engine) -> List[Dict[str, Any]]:
    """Every in-flight request on one engine, classified exactly the
    way the engine's own bookkeeping classifies it: the swap ledger
    first (a preempted request is also re-queued — `swapped` wins),
    then prefill frontiers, live decode rows, and the scheduler queue.
    Finished/popped requests are not state; read `results`/`finished`
    for those."""
    now = engine._clock()
    rows: List[Dict[str, Any]] = []
    swapped_ids = set(engine._swapped) if engine.paged else set()
    prefill_only = bool(getattr(engine, "prefill_only", False))
    for b, st in engine._row_prefill.items():
        rows.append(_req_row(engine, st.req, "prefilling", row=b,
                             prefill_pos=st.pos, now=now))
    for b, req in enumerate(engine.row_req):
        if req is not None and b not in engine._row_prefill:
            # A prefill-class engine never decodes: a bound row past
            # its prefill frontier is PARKED for export, not emitting.
            status = "handoff" if prefill_only else "decoding"
            rows.append(_req_row(engine, req, status, row=b,
                                 now=now))
    for entry in engine.scheduler.queued_state():
        req = entry.get("request")
        if req is None:
            # Custom policy exposing ids only: a thin queued row.
            rows.append({"req_id": entry["req_id"],
                         "engine_id": engine.engine_id,
                         "status": "queued", "row": None,
                         "age_s": None,
                         "engine_draining": bool(engine.draining)})
            continue
        # An imported handoff waiting for decode admission also sits
        # in the swap ledger (its KV pre-seed) — "handoff" wins so the
        # request is counted exactly once, in its true phase.
        if getattr(req, "handoff", False):
            status = "handoff"
        elif req.req_id in swapped_ids:
            status = "swapped"
        else:
            status = "queued"
        row = _req_row(engine, req, status, now=now)
        if req.req_id in swapped_ids:
            swap = engine._swapped[req.req_id]
            row["swap_blocks"] = swap.n_blocks
            row["swap_resident"] = swap.k is not None
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# Query functions
# ---------------------------------------------------------------------------

REQUEST_STATUSES = ("queued", "prefilling", "decoding", "swapped",
                    "handoff", "recovering", "draining")


def list_engines(limit: int = 1000) -> List[Dict[str, Any]]:
    """One row per live registered engine (see `engine_state`)."""
    return [engine_state(e) for e in engines()[:limit]]


def list_requests(status: Optional[str] = None,
                  engine_id: Optional[str] = None,
                  limit: int = 1000) -> List[Dict[str, Any]]:
    """Every in-flight request across registered engines.

    ``status`` filters to one phase (queued / prefilling / decoding /
    swapped / handoff / recovering) or to ``draining`` — all requests,
    any phase, on engines that have begun draining. ``engine_id``
    restricts to one engine (``recovering`` rows and host-parked
    ``handoff`` rows belong to a FLEET, not an engine, so an engine_id
    filter excludes them)."""
    if status is not None and status not in REQUEST_STATUSES:
        raise ValueError(
            f"unknown status {status!r} "
            f"(expected one of {'|'.join(REQUEST_STATUSES)})")
    rows: List[Dict[str, Any]] = []
    for eng in engines():
        if engine_id is not None and eng.engine_id != engine_id:
            continue
        rows.extend(engine_requests(eng))
    if engine_id is None:
        # Failed-over requests waiting out their retry backoff are
        # fleet-side state (no engine holds them yet).
        for fleet in fleets():
            for r in fleet.recovering_requests():
                rows.append({**r, "engine_id": None,
                             "status": "recovering", "row": None,
                             "fleet": fleet.fleet_id,
                             "age_s": None,
                             "engine_draining": False})
            # Exports parked between replica classes (disaggregated
            # fleets only): host-side payloads no engine holds yet.
            for r in getattr(fleet, "handoff_requests", list)():
                rows.append({**r, "engine_id": None,
                             "status": "handoff", "row": None,
                             "fleet": fleet.fleet_id,
                             "age_s": None,
                             "engine_draining": False})
    if status == "draining":
        rows = [r for r in rows if r["engine_draining"]]
    elif status is not None:
        rows = [r for r in rows if r["status"] == status]
    return rows[:limit]


def list_kv_pools(limit: int = 1000) -> List[Dict[str, Any]]:
    """One row per engine that owns KV block storage: the paged
    engine's unified pool (refcount ledger included) or the dense
    engine's prefix-cache pool. Engines with neither are omitted."""
    rows: List[Dict[str, Any]] = []
    for eng in engines():
        pool = eng.kv_pool
        prefix = eng._prefix
        if pool is None and prefix is None:
            continue
        row: Dict[str, Any] = {
            "engine_id": eng.engine_id,
            "kind": "paged" if pool is not None else "prefix",
            "block_tokens": eng.prefix_block,
            # Quantized-KV plane: storage dtype (None = dense kv_dtype)
            # and the byte cost one block/token actually pays, scale
            # slab included. getattr defaults keep pre-quant engine
            # objects (or test doubles) listable.
            "quant": getattr(eng, "kv_quant", None),
            "bytes_per_block": float(
                getattr(eng, "kv_bytes_per_block", 0.0)),
            "bytes_per_token": float(
                getattr(eng, "kv_bytes_per_token", 0.0)),
        }
        if pool is not None:
            row.update(pool.snapshot())
            row["occupancy"] = (pool.blocks_in_use / pool.blocks_total
                                if pool.blocks_total else 0.0)
        if prefix is not None:
            row["prefix_blocks_in_use"] = prefix.blocks_in_use
            row["prefix_blocks_total"] = prefix.blocks_total
            row["evictable_blocks"] = prefix.evictable_blocks()
            if pool is None:
                row["blocks_total"] = prefix.blocks_total
                row["blocks_in_use"] = prefix.blocks_in_use
                row["occupancy"] = (
                    prefix.blocks_in_use / prefix.blocks_total
                    if prefix.blocks_total else 0.0)
        rows.append(row)
    return rows[:limit]


def _phase_counts(rows: List[Dict[str, Any]]) -> Dict[str, int]:
    counts = {s: 0 for s in REQUEST_STATUSES if s != "draining"}
    for r in rows:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
    return counts


def _health_counts(fleet) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for state in fleet.replica_health().values():
        counts[state] = counts.get(state, 0) + 1
    return counts


def summarize_fleet() -> Dict[str, Any]:
    """`ray status`-shaped rollup: one block per registered fleet plus
    totals over every registered engine (fleet members and loose
    engines alike). Built from the same read-only snapshots as the
    list_* calls — unlike `LLMFleet.stats()` it publishes NO gauges,
    so polling it cannot perturb the metric plane."""
    engine_rows = list_engines()
    request_rows = list_requests()
    by_engine: Dict[str, List[Dict[str, Any]]] = {}
    for r in request_rows:
        by_engine.setdefault(r["engine_id"], []).append(r)

    fleet_blocks: List[Dict[str, Any]] = []
    for fleet in fleets():
        members = [r for r in engine_rows
                   if r["fleet"] == fleet.fleet_id]
        member_reqs = [rr for r in members
                       for rr in by_engine.get(r["engine_id"], [])]
        running = sum(1 for r in members if not r["draining"])
        fleet_blocks.append({
            "fleet_id": fleet.fleet_id,
            "router": type(fleet.router).__name__,
            "replicas": len(members),
            "replicas_running": running,
            "replicas_draining": len(members) - running,
            "autoscaling": fleet.autoscaler is not None,
            "tp_degree_max": max(
                (r["tp_degree"] for r in members), default=1),
            "queue_depth": sum(r["queue_depth"] for r in members),
            "slot_occupancy_mean": (
                sum(r["slot_occupancy"] for r in members) / len(members)
                if members else 0.0),
            "kv_used_fraction_mean": (
                sum(r["kv_used_fraction"] for r in members)
                / len(members) if members else 0.0),
            "requests_routed": fleet.requests_routed,
            "requests_shed": fleet.requests_shed,
            "requests": _phase_counts(member_reqs),
            # Fault-tolerance plane: replica health census + recovery
            # counters (all host-side reads, like everything here).
            "health": _health_counts(fleet),
            "replicas_failed": fleet.replicas_failed,
            "requests_recovering": len(fleet.recovering_requests()),
            "requests_recovered": fleet.requests_recovered,
            "requests_failed": fleet.requests_failed,
            "retries": fleet.retries,
            "tokens_lost_to_failure": fleet.tokens_lost_to_failure,
            # Disaggregated plane (zeros for colocated fleets).
            "disaggregated": bool(
                getattr(fleet, "disaggregated", False)),
            "replicas_prefill": sum(
                1 for r in members
                if r.get("replica_class") == "prefill"),
            "replicas_decode": sum(
                1 for r in members
                if r.get("replica_class") == "decode"),
            "handoffs": int(getattr(fleet, "handoffs", 0)),
        })

    attached = {r["engine_id"] for r in engine_rows
                if r["fleet"] is not None}
    return {
        "fleets": fleet_blocks,
        "engines_total": len(engine_rows),
        "engines_unattached": len(engine_rows) - len(attached),
        "requests": _phase_counts(request_rows),
        "requests_inflight": len(request_rows),
    }
