"""State API — programmatic queries over live cluster state.

Reference: python/ray/util/state/api.py (list_actors/list_nodes/
list_tasks/list_objects/list_placement_groups + summaries) backed by the
GCS actor/node/task tables; here each call is one GCS RPC through the
connected worker.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _gcs(method: str, data: Optional[dict] = None):
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs_call(method, data or {})


def _filter(rows: List[dict], filters) -> List[dict]:
    """filters: list of (key, predicate-str, value) like the reference's
    state API ('=' and '!=' supported)."""
    if not filters:
        return rows
    out = []
    for row in rows:
        keep = True
        for key, op, value in filters:
            have = row.get(key)
            if op == "=":
                keep = keep and (str(have) == str(value))
            elif op == "!=":
                keep = keep and (str(have) != str(value))
            else:
                raise ValueError(f"unsupported filter op {op!r}")
        if keep:
            out.append(row)
    return out


def list_actors(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("list_actors")
    for r in rows:
        if isinstance(r.get("actor_id"), bytes):
            r["actor_id"] = r["actor_id"].hex()
    return _filter(rows, filters)[:limit]


def list_nodes(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("get_nodes")
    for r in rows:
        if isinstance(r.get("node_id"), bytes):
            r["node_id"] = r["node_id"].hex()
    return _filter(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    """Task state transitions recorded by workers' task event buffers
    (reference: GcsTaskManager-backed `ray list tasks`). Collapses events
    to one row per task with its latest state."""
    events = _gcs("list_task_events", {"limit": 100_000})
    # Workers flush on independent cadences; GCS arrival order is not
    # event order. Merge by per-event timestamp.
    events = sorted(events, key=lambda e: e.get("time", 0.0))
    # Profile spans ride the same pipeline but are not tasks.
    events = [e for e in events if e.get("state") != "PROFILE"]
    by_task: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        tid = ev.get("task_id")
        tid = tid.hex() if isinstance(tid, bytes) else str(tid)
        row = by_task.setdefault(tid, {"task_id": tid})
        row.update({
            k: (v.hex() if isinstance(v, bytes) else v)
            for k, v in ev.items() if k != "task_id"})
    rows = list(by_task.values())
    return _filter(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    """Objects with known locations in the GCS object directory."""
    rows = _gcs("list_object_locations", {})
    return _filter(rows, filters)[:limit]


def list_placement_groups(filters=None,
                          limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("list_placement_groups", {})
    for r in rows:
        if isinstance(r.get("pg_id"), bytes):
            r["pg_id"] = r["pg_id"].hex()
        if isinstance(r.get("bundle_locations"), dict):
            r["bundle_locations"] = {
                k: (v.hex() if isinstance(v, bytes) else v)
                for k, v in r["bundle_locations"].items()}
    return _filter(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("list_jobs", {})
    return _filter(rows, filters)[:limit]


def cluster_resources() -> Dict[str, Dict[str, float]]:
    return _gcs("cluster_resources")


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for row in list_tasks(limit=100_000):
        state = row.get("state", "UNKNOWN")
        counts[state] = counts.get(state, 0) + 1
    return counts


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for row in list_actors(limit=100_000):
        state = row.get("state", "UNKNOWN")
        counts[state] = counts.get(state, 0) + 1
    return counts
