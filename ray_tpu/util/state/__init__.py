"""State API — programmatic queries over live cluster state.

Reference: python/ray/util/state/api.py (list_actors/list_nodes/
list_tasks/list_objects/list_placement_groups + summaries) backed by the
GCS actor/node/task tables; here each call is one GCS RPC through the
connected worker.

The SERVING-plane state API (list_engines / list_requests /
list_kv_pools / summarize_fleet over live DecodeEngine/LLMFleet
registrations) lives in the `serving` submodule and is re-exported
here, so `from ray_tpu.util import state; state.list_engines()` works
the same way the cluster queries do.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.util.state.serving import (  # noqa: F401
    engine_requests, engine_state, engines, fleets, list_engines,
    list_kv_pools, list_requests, register_engine, register_fleet,
    register_server, reset_serving_state, servers, summarize_fleet)


def _gcs(method: str, data: Optional[dict] = None):
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs_call(method, data or {})


def _coerce_pair(have: Any, value: Any):
    """Numeric comparison when both sides parse as numbers, else string
    comparison (matches the reference's predicate semantics)."""
    try:
        return float(have), float(value)
    except (TypeError, ValueError):
        return str(have), str(value)


def _match(have: Any, op: str, value: Any) -> bool:
    if op == "=":
        return str(have) == str(value)
    if op == "!=":
        return str(have) != str(value)
    if op in ("<", "<=", ">", ">="):
        a, b = _coerce_pair(have, value)
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        return a >= b
    if op == "contains":
        return str(value) in str(have)
    if op == "in":
        vals = value if isinstance(value, (list, tuple, set)) else \
            [v.strip() for v in str(value).split(",")]
        return str(have) in {str(v) for v in vals}
    raise ValueError(
        f"unsupported filter op {op!r} "
        "(supported: = != < <= > >= contains in)")


def _filter(rows: List[dict], filters) -> List[dict]:
    """filters: list of (key, predicate-str, value) — the reference's
    state API predicate set: = != < <= > >= plus contains / in."""
    if not filters:
        return rows
    return [row for row in rows
            if all(_match(row.get(key), op, value)
                   for key, op, value in filters)]


def list_actors(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("list_actors")
    for r in rows:
        if isinstance(r.get("actor_id"), bytes):
            r["actor_id"] = r["actor_id"].hex()
    return _filter(rows, filters)[:limit]


def list_nodes(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("get_nodes")
    for r in rows:
        if isinstance(r.get("node_id"), bytes):
            r["node_id"] = r["node_id"].hex()
    return _filter(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    """Task state transitions recorded by workers' task event buffers
    (reference: GcsTaskManager-backed `ray list tasks`). Collapses events
    to one row per task with its latest state."""
    events = _gcs("list_task_events", {"limit": 100_000})
    # Workers flush on independent cadences; GCS arrival order is not
    # event order. Merge by per-event timestamp.
    events = sorted(events, key=lambda e: e.get("time", 0.0))
    # Profile spans ride the same pipeline but are not tasks.
    events = [e for e in events if e.get("state") != "PROFILE"]
    by_task: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        tid = ev.get("task_id")
        tid = tid.hex() if isinstance(tid, bytes) else str(tid)
        row = by_task.setdefault(tid, {"task_id": tid})
        row.update({
            k: (v.hex() if isinstance(v, bytes) else v)
            for k, v in ev.items() if k != "task_id"})
    rows = list(by_task.values())
    return _filter(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 1000,
                 detail: bool = False) -> List[Dict[str, Any]]:
    """Cluster-wide object listing: the GCS object directory (locations,
    spill URLs) joined with every alive raylet's shm-store table (size,
    pin count) — `ray list objects` over the DISTRIBUTED object tables,
    not just the head's view. ``detail=False`` skips the per-raylet
    sweep and returns the directory only."""
    directory = {r["object_id"]: dict(r)
                 for r in _gcs("list_object_locations", {})}
    if detail:
        import asyncio

        from ray_tpu.core import rpc

        async def sweep():
            rows = []
            for node in _gcs("get_nodes"):
                if node.get("state") != "ALIVE":
                    continue
                try:
                    host, port = node["address"].rsplit(":", 1)
                    conn = await rpc.connect(host, int(port), timeout=2.0)
                    try:
                        rows.extend(await conn.call(
                            "list_store_objects", {"limit": limit}))
                    finally:
                        await conn.close()
                except Exception:
                    continue  # node died mid-sweep: best-effort listing
            return rows

        for shard in asyncio.run(sweep()):
            row = directory.setdefault(
                shard["object_id"], {"object_id": shard["object_id"],
                                     "node_ids": [shard["node_id"]]})
            row["size_bytes"] = shard["size_bytes"]
            row["pins"] = shard.get("pins", 0)
    return _filter(list(directory.values()), filters)[:limit]


def list_placement_groups(filters=None,
                          limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("list_placement_groups", {})
    for r in rows:
        if isinstance(r.get("pg_id"), bytes):
            r["pg_id"] = r["pg_id"].hex()
        if isinstance(r.get("bundle_locations"), dict):
            r["bundle_locations"] = {
                k: (v.hex() if isinstance(v, bytes) else v)
                for k, v in r["bundle_locations"].items()}
    return _filter(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = _gcs("list_jobs", {})
    return _filter(rows, filters)[:limit]


def cluster_resources() -> Dict[str, Dict[str, float]]:
    return _gcs("cluster_resources")


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for row in list_tasks(limit=100_000):
        state = row.get("state", "UNKNOWN")
        counts[state] = counts.get(state, 0) + 1
    return counts


def summarize_actors() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for row in list_actors(limit=100_000):
        state = row.get("state", "UNKNOWN")
        counts[state] = counts.get(state, 0) + 1
    return counts
