"""joblib backend over the actor runtime.

Reference: python/ray/util/joblib/ — ``register_ray()`` registers a
joblib parallel backend whose pool is the cluster-wide
:class:`ray_tpu.util.multiprocessing.Pool`, so scikit-learn et al.
(`with joblib.parallel_backend("ray_tpu"): ...`) fan out across the
cluster unchanged.
"""

from __future__ import annotations


def register_ray() -> None:
    """Register the 'ray_tpu' joblib backend (idempotent)."""
    from joblib import register_parallel_backend
    from joblib._parallel_backends import MultiprocessingBackend

    from ray_tpu.util.multiprocessing import Pool

    class RayTpuBackend(MultiprocessingBackend):
        # Same trick as the reference's RayBackend: reuse joblib's
        # multiprocessing plumbing, swapping in the actor Pool.
        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if n_jobs == -1:
                # Connect NOW if needed — resolving -1 to a single job
                # on a cluster Pool() would join anyway silently
                # serializes the workload.
                if not ray_tpu.is_initialized():
                    ray_tpu.init()
                return max(1, int(
                    ray_tpu.cluster_resources().get("CPU", 1)))
            return max(1, int(n_jobs or 1))

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **_memmapping_args):
            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            pool = getattr(self, "_pool", None)
            if pool is not None:
                pool.terminate()
                self._pool = None

    register_parallel_backend("ray_tpu", RayTpuBackend)
