"""ray_tpu — a TPU-native distributed AI runtime.

A brand-new framework with the capabilities of Ray (reference:
python/ray/__init__.py in Deegue/ray @ 2024-10-08), designed JAX/XLA-first:

- Core: task/actor runtime with a shared-memory object store, ownership-based
  reference counting, leases, and placement groups (incl. slice-atomic gang
  scheduling of TPU pod slices).
- parallel/ops/models: GSPMD mesh utilities, Pallas kernels (flash/ring
  attention), and flagship JAX models.
- Libraries: train (JaxTrainer), data (streaming datasets), tune
  (hyperparameter search), serve (model serving), rllib (RL).

Public core API parity target: ``ray.init/remote/get/put/wait``
(reference python/ray/_private/worker.py:1225,2551; remote_function.py:40).
"""

from ray_tpu._private.worker import (
    init,
    shutdown,
    is_initialized,
    get,
    put,
    wait,
    kill,
    cancel,
    get_runtime_context,
    cluster_resources,
    available_resources,
    nodes,
)
from ray_tpu._private.api import remote, method
from ray_tpu.core.generator import ObjectRefGenerator
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.actor import ActorHandle, ActorClass, get_actor

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_runtime_context",
    "cluster_resources",
    "available_resources",
    "nodes",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorHandle",
    "ActorClass",
    "get_actor",
    "__version__",
]


def __getattr__(name):
    # Lazy imports of subpackages so that `import ray_tpu` stays fast and
    # JAX-free for pure-runtime users.
    import importlib

    if name in ("train", "data", "tune", "serve", "rllib", "util",
                "parallel", "ops", "models", "collective", "dag", "air",
                "workflow"):
        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
