"""Core-runtime microbenchmarks (reference: python/ray/_private/ray_perf.py
+ release/microbenchmark/): task throughput, actor call latency, object
store put/get bandwidth. Prints one JSON line per metric.

Each metric is measured over several trials and reported as the MEDIAN:
this box runs co-tenant load (round-3 verdict: a single capture swung 2x
under background activity), so single-shot numbers are noise.

Run: python microbench.py [--quick]
"""

import json
import os
import statistics
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# The sharded-dispatch section sweeps tensor-parallel degree; off-TPU
# that needs a forced multi-device CPU world, set before jax initializes
# (if jax is already up with fewer devices the section skips tp=4).
if "jax" not in sys.modules and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        (os.environ.get("XLA_FLAGS", "") +
         " --xla_force_host_platform_device_count=8").strip())

TRIALS = 3


def timed_median(fn, n, trials=TRIALS):
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fn()
        rates.append(n / (time.perf_counter() - t0))
    return statistics.median(rates)


def _decode_dispatch_section(quick: bool) -> list:
    """Decode-step dispatch overhead for the fused serving engine
    (models/engine.py): per-step WALL time (engine.step: host
    bookkeeping + dispatch + the one [H, B] token-block transfer +
    replay) vs DEVICE time (the bare jitted _decode_multi program,
    chained through its donated buffers), plus transfers per token, at
    horizon 1 (the historical per-token cadence) and the default 8.
    wall - device is the per-step host tax the fused horizon amortizes.
    Runs anywhere — `JAX_PLATFORMS=cpu python microbench.py` included
    (nano model; the OVERHEAD is host-side and real on any backend)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine, _decode_multi

    cfg = LlamaConfig.nano()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    B, prompt_len, new_tokens = 4, 16, 16 if quick else 64
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=prompt_len).tolist()
               for _ in range(B)]
    max_len = prompt_len + new_tokens + 1
    results = []

    def fill(horizon):
        # pipeline_depth=1: this section measures the SYNCHRONOUS
        # per-step cost (dispatch + blocking pull + replay); the
        # pipelined overlap is measured by _dispatch_gap_section.
        eng = DecodeEngine(params, cfg, batch_slots=B, max_len=max_len,
                           decode_horizon=horizon, pipeline_depth=1,
                           enable_metrics=False)
        for p in prompts:
            eng.submit(p, new_tokens)
        eng.step(horizon=1)          # admit all rows (+1 token each)
        return eng

    for H in (1, 8):
        fill(H).run()                # warmup: compile prefill + this H

        # WALL: full engine steps, horizon pinned; count tokens (a
        # fused step emits up to H per row).
        wall_ms, toks, steps = [], 0, 0
        for _ in range(TRIALS):
            eng = fill(H)
            t0 = time.perf_counter()
            while eng.pending():
                ev = eng.step(horizon=H)
                steps += 1
                toks += sum(len(t) for t in ev.values())
            wall_ms.append((time.perf_counter() - t0) * 1000)
        n_steps = steps // TRIALS
        wall = statistics.median(wall_ms) / max(1, n_steps)
        syncs_per_tok = eng.stats()["host_syncs_per_token"]

        # DEVICE: the bare fused program, chained through its donated
        # cache/last_logits (no host replay, no block pull beyond the
        # final sync).
        eng = fill(H)
        dev_ms = []
        args = (jnp.asarray(eng.row_len),
                jnp.asarray(np.array([True] * B)),
                jnp.asarray(eng.row_budget + 10_000),
                jnp.asarray(eng._tok_idx), jnp.asarray(eng._row_keys))
        cache, last = eng.cache, eng._last_logits
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            for _ in range(n_steps):
                toks_d, cache, last, *_rest = _decode_multi(
                    eng.params, cache, last, *args,
                    jnp.asarray(np.array([True] * B)), eng.temperature,
                    cfg, H, True, None, None, None)
            jax.block_until_ready(toks_d)
            dev_ms.append((time.perf_counter() - t0) * 1000 /
                          max(1, n_steps))
        dev = statistics.median(dev_ms)

        results.append((f"engine_decode_wall_ms_per_step_h{H}",
                        wall, "ms"))
        results.append((f"engine_decode_device_ms_per_step_h{H}",
                        dev, "ms"))
        results.append((f"engine_decode_host_overhead_ms_per_step_h{H}",
                        max(0.0, wall - dev), "ms"))
        results.append((f"engine_decode_transfers_per_token_h{H}",
                        syncs_per_tok, "syncs/token"))
    return results


def _spec_dispatch_section(quick: bool) -> list:
    """ONE speculative dispatch vs window+1 plain dispatches: the spec
    engine's whole round (draft scan of W proposals + one batched
    verify + on-device acceptance) is a single program launch emitting
    up to W+1 verified tokens per row, where the horizon-1 plain
    engine pays W+1 separate dispatch+drain round trips for the same
    tokens. Draft == target (perfect acceptance), so the token counts
    divide exactly and the per-token ratio isolates the dispatch
    amortization — the host-side overhead is real on any backend.
    pipeline_depth=1 on both engines: this measures the synchronous
    cost; run-ahead overlap is _dispatch_gap_section's job."""
    import jax  # noqa: F401
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine

    cfg = LlamaConfig.nano()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    B, prompt_len, W = 4, 16, 4
    new_tokens = 20 if quick else 40     # multiples of W+1: no
    #                                      truncated final round
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=prompt_len).tolist()
               for _ in range(B)]
    max_len = prompt_len + new_tokens + W + 1

    def make(spec):
        kw = (dict(draft_params=params, draft_cfg=cfg, spec_window=W)
              if spec else dict(decode_horizon=1))
        eng = DecodeEngine(params, cfg, batch_slots=B, max_len=max_len,
                           pipeline_depth=1, enable_metrics=False,
                           **kw)
        for p in prompts:
            eng.submit(p, new_tokens)
        return eng

    per_tok = {}
    results = []
    for spec in (False, True):
        make(spec).run()                 # warmup: compile this path
        ms = []
        for _ in range(TRIALS):
            eng = make(spec)
            t0 = time.perf_counter()
            eng.run()
            ms.append((time.perf_counter() - t0) * 1000)
        med = statistics.median(ms)
        total = B * new_tokens
        per_tok[spec] = med / total
        s = eng.stats()
        if spec:
            disp = max(1, int(s["spec_dispatches"]))
            results.append((f"engine_spec_wall_ms_per_dispatch_w{W}",
                            med / disp, "ms"))
            results.append((f"engine_spec_tokens_per_dispatch_w{W}",
                            total / disp, "tokens"))
            results.append((f"engine_spec_acceptance_rate_w{W}",
                            s["spec_acceptance_rate"], "frac"))
            results.append((f"engine_spec_ms_per_token_w{W}",
                            per_tok[True], "ms"))
        else:
            results.append(("engine_plain_ms_per_token_h1",
                            per_tok[False], "ms"))
    results.append((f"engine_spec_dispatch_speedup_w{W}_vs_h1",
                    per_tok[False] / per_tok[True]
                    if per_tok[True] else 0.0, "x"))
    return results


def _sharded_dispatch_section(quick: bool) -> list:
    """Per-step cost of the TENSOR-PARALLEL engine vs the plain one:
    wall ms/step (engine.step over a tp mesh: host bookkeeping +
    sharded dispatch + the one replicated [H, B] token-block pull) and
    device ms/step (the bare jitted _decode_multi with the engine's
    NamedShardings, chained through its donated buffers) at tp=1 (the
    unsharded control) and tp=4, plus host bytes/token at each degree.
    The gate: the host-side numbers must NOT scale with chip count —
    the choke point stays one replicated block pull per fused step, so
    bytes/token is flat and wall - device stays the same host tax the
    plain engine pays. Runs anywhere (the module-top flag forces an
    8-device CPU world; skips tp=4 if the backend has fewer devices)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine, _decode_multi

    cfg = LlamaConfig.nano()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    B, prompt_len, H = 4, 16, 8
    new_tokens = 16 if quick else 64
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=prompt_len).tolist()
               for _ in range(B)]
    max_len = prompt_len + new_tokens + 1
    results = []

    def fill(tp):
        # pipeline_depth=1: the synchronous per-step cost is the
        # number under test (overlap is _dispatch_gap_section's job);
        # tp=1 is the PLAIN engine, not a 1-device mesh, so the sweep
        # prices the sharding machinery itself.
        kw = {} if tp == 1 else {"tp": tp}
        eng = DecodeEngine(params, cfg, batch_slots=B, max_len=max_len,
                           decode_horizon=H, pipeline_depth=1,
                           enable_metrics=False, **kw)
        for p in prompts:
            eng.submit(p, new_tokens)
        eng.step(horizon=1)          # admit all rows (+1 token each)
        return eng

    for tp in (1, 4):
        if tp > len(jax.devices()):
            continue
        fill(tp).run()               # warmup: compile prefill + decode

        wall_ms, toks, steps = [], 0, 0
        for _ in range(TRIALS):
            eng = fill(tp)
            t0 = time.perf_counter()
            while eng.pending():
                ev = eng.step(horizon=H)
                steps += 1
                toks += sum(len(t) for t in ev.values())
            wall_ms.append((time.perf_counter() - t0) * 1000)
        n_steps = steps // TRIALS
        wall = statistics.median(wall_ms) / max(1, n_steps)
        bytes_per_tok = eng.stats()["host_transfer_bytes_per_token"]

        # DEVICE: the bare fused program under this tp's shardings,
        # chained through its donated cache/last_logits.
        eng = fill(tp)
        dev_ms = []
        args = (jnp.asarray(eng.row_len),
                jnp.asarray(np.array([True] * B)),
                jnp.asarray(eng.row_budget + 10_000),
                jnp.asarray(eng._tok_idx), jnp.asarray(eng._row_keys))
        cache, last = eng.cache, eng._last_logits
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            for _ in range(n_steps):
                toks_d, cache, last, *_rest = _decode_multi(
                    eng.params, cache, last, *args,
                    jnp.asarray(np.array([True] * B)), eng.temperature,
                    cfg, H, True, None, None, None,
                    shardings=eng._shardings)
            jax.block_until_ready(toks_d)
            dev_ms.append((time.perf_counter() - t0) * 1000 /
                          max(1, n_steps))
        dev = statistics.median(dev_ms)

        results.append((f"engine_sharded_wall_ms_per_step_tp{tp}",
                        wall, "ms"))
        results.append((f"engine_sharded_device_ms_per_step_tp{tp}",
                        dev, "ms"))
        results.append((f"engine_sharded_host_bytes_per_token_tp{tp}",
                        bytes_per_tok, "bytes/token"))
    return results


def _dispatch_gap_section(quick: bool) -> list:
    """Host gap between consecutive fused-decode DISPATCHES — the
    window in which the device has NOTHING queued and starves on host
    bookkeeping — sync (pipeline_depth=1) vs pipelined (depth=2), on a
    pure-decode workload (all slots admitted up front, queue empty).

    Measured from the engine's own host event stream: each blocking
    token-block pull (`_device_get`) that leaves ZERO dispatched
    programs in flight opens a starvation window, closed by the next
    `_decode_multi` launch. The synchronous loop opens one EVERY block
    (pull, then the whole O(H*B) replay, then dispatch — the device
    idles throughout); the pipelined loop dispatches step N+1 BEFORE
    pulling step N, so a pull almost never drains the device dry and
    the per-block gap collapses to ~0 (flush points are the residue).
    CPU dry-run capable: the gap is host-side wall time and the
    dispatch-before-pull inversion is real on any backend
    (`JAX_PLATFORMS=cpu python microbench.py`)."""
    import jax
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models import engine as engine_mod
    from ray_tpu.models.engine import DecodeEngine

    cfg = LlamaConfig.nano()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    B, prompt_len = 4, 16
    new_tokens = 32 if quick else 128
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=prompt_len).tolist()
               for _ in range(B)]
    max_len = prompt_len + new_tokens + 1

    def drive(depth):
        eng = DecodeEngine(params, cfg, batch_slots=B, max_len=max_len,
                           decode_horizon=8, pipeline_depth=depth,
                           enable_metrics=False)
        for p in prompts:
            eng.submit(p, new_tokens)
        eng.run()

    def starvation_gaps(events):
        """events: ("dispatch", t) at launch / ("get", t) at pull
        return. A pull that leaves in-flight == 0 starts a starvation
        window; the next dispatch ends it."""
        gaps, inflight, open_t = [], 0, None
        for kind, t in events:
            if kind == "dispatch":
                if open_t is not None:
                    gaps.append((t - open_t) * 1000)
                    open_t = None
                inflight += 1
            else:
                inflight -= 1
                if inflight == 0:
                    open_t = t
        return gaps

    results = []
    real_multi = engine_mod._decode_multi
    real_get = engine_mod._device_get
    for depth in (1, 2):
        drive(depth)                 # warmup: compile every program
        events = []

        def timed_multi(*a, **k):
            events.append(("dispatch", time.perf_counter()))
            return real_multi(*a, **k)

        def timed_get(x):
            out = real_get(x)
            events.append(("get", time.perf_counter()))
            return out

        engine_mod._decode_multi = timed_multi
        engine_mod._device_get = timed_get
        gaps = []
        try:
            for _ in range(TRIALS):
                events.clear()       # windows never span engines
                drive(depth)
                gaps.extend(starvation_gaps(events))
        finally:
            engine_mod._decode_multi = real_multi
            engine_mod._device_get = real_get
        # Mean, not median: the pipelined loop's distribution is mostly
        # exact zeros (pre-dispatched blocks) with a few flush-point
        # gaps — the mean keeps that residue visible instead of
        # reporting a flat 0.
        results.append((f"engine_dispatch_gap_ms_d{depth}",
                        statistics.fmean(gaps) if gaps else 0.0,
                        "ms"))
    return results


def _prefix_admission_section(quick: bool) -> list:
    """Admission cost with the shared-prefix KV cache
    (models/engine.py + models/prefix_cache.py): per prefix length,
    the wall ms and host syncs of admitting a request COLD (full
    prompt prefill, pool copy-out of the novel blocks) vs WARM (pool
    copy-in of the cached blocks + suffix-only prefill). The gap is
    what prefix reuse buys every repeat of a system prompt. Runs
    anywhere — the nano model makes the prefill cost small but the
    cold/warm ORDERING and the sync counts are real on any backend."""
    import jax
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine

    lens = (128,) if quick else (128, 512, 2048)
    suffix_len, new_tokens, T = 16, 4, 32
    results = []
    for P in lens:
        cfg = LlamaConfig.nano(max_seq_len=P + suffix_len + new_tokens + 8)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(P)
        prefix = rng.randint(1, cfg.vocab_size, size=P).tolist()

        def make():
            return DecodeEngine(params, cfg, batch_slots=2,
                                max_len=cfg.max_seq_len,
                                prefix_cache=True, prefix_block=T,
                                enable_metrics=False)

        def admit_once(eng):
            """Submit one prefix+fresh-suffix request, time its
            admission step, return (ms, host syncs)."""
            p = prefix + rng.randint(1, cfg.vocab_size,
                                     size=suffix_len).tolist()
            rid = eng.submit(p, new_tokens)
            syncs0 = eng.host_syncs
            t0 = time.perf_counter()
            eng.step(horizon=1)
            ms = (time.perf_counter() - t0) * 1000
            syncs = eng.host_syncs - syncs0
            while eng.pending():          # drain so the slot frees
                eng.step(horizon=1)
            eng.pop_result(rid)
            return ms, syncs

        admit_once(make())                # warmup eng: compile cold path
        warm_eng = make()
        admit_once(warm_eng)              # seed + compile warm path
        admit_once(warm_eng)

        cold_ms, warm_ms = [], []
        cold_syncs = warm_syncs = 0
        for _ in range(TRIALS):
            eng = make()                  # empty trie: first is cold
            ms, cold_syncs = admit_once(eng)
            cold_ms.append(ms)
            ms, warm_syncs = admit_once(eng)   # trie now holds prefix
            warm_ms.append(ms)
        results.append((f"engine_prefix_admission_cold_ms_p{P}",
                        statistics.median(cold_ms), "ms"))
        results.append((f"engine_prefix_admission_warm_ms_p{P}",
                        statistics.median(warm_ms), "ms"))
        results.append((f"engine_prefix_admission_cold_syncs_p{P}",
                        float(cold_syncs), "syncs"))
        results.append((f"engine_prefix_admission_warm_syncs_p{P}",
                        float(warm_syncs), "syncs"))
    return results


def _paged_gather_section(quick: bool) -> list:
    """Block-table-gather overhead of paged attention
    (ops/attention.py `paged_attention` vs the dense
    `_cached_attention` it must stay in op-for-op lockstep with): per
    max_len span, the wall ms of one fused decode-shaped attention
    over (a) a contiguous dense cache row and (b) the same K/V read
    through a per-row block table out of a 4x-oversized pool. The
    delta is the pure cost of the paged indirection — the price the
    engine pays per decode step for pool-bounded admission and
    zero-copy prefix shares. Runs anywhere: on CPU both lower to the
    same XLA reference einsums, so the gather overhead is the real
    quantity measured; Mosaic kernels change the constant, not the
    comparison's meaning."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.generate import _cached_attention
    from ray_tpu.ops.attention import paged_attention

    B, H, KV, D, T = 8, 4, 2, 16, 16
    spans = (256,) if quick else (256, 1024)
    results = []
    for span in spans:
        MB = span // T
        NB = 4 * MB + 1                    # 4x oversized pool + null
        key = jax.random.PRNGKey(span)
        q = jax.random.normal(key, (B, 1, H, D), jnp.float32)
        dense_k = jax.random.normal(key, (B, span, KV, D), jnp.float32)
        dense_v = dense_k + 1.0
        pool_k = jax.random.normal(key, (NB, T, KV, D), jnp.float32)
        pool_v = pool_k + 1.0
        # scattered tables: stride the pool so the gather is non-unit
        bt = (1 + (jnp.arange(B * MB) * 7) % (NB - 1)).reshape(B, MB)
        bt = bt.astype(jnp.int32)
        slots = jnp.full((B, 1), span - 1, jnp.int32)

        dense_fn = jax.jit(lambda q, k, v: _cached_attention(
            q, k, v, slots, span, None))
        paged_fn = jax.jit(lambda q, k, v: paged_attention(
            q, k, v, bt, slots, kv_valid_len=span))
        dense_fn(q, dense_k, dense_v).block_until_ready()
        paged_fn(q, pool_k, pool_v).block_until_ready()

        def run(fn, *args):
            ts = []
            for _ in range(TRIALS):
                t0 = time.perf_counter()
                for _ in range(20):
                    out = fn(*args)
                out.block_until_ready()
                ts.append((time.perf_counter() - t0) / 20 * 1000)
            return statistics.median(ts)

        d_ms = run(dense_fn, q, dense_k, dense_v)
        p_ms = run(paged_fn, q, pool_k, pool_v)
        results.append((f"paged_attention_dense_ms_s{span}", d_ms,
                        "ms"))
        results.append((f"paged_attention_paged_ms_s{span}", p_ms,
                        "ms"))
        results.append((f"paged_attention_gather_overhead_pct_s{span}",
                        (p_ms - d_ms) / d_ms * 100.0 if d_ms else 0.0,
                        "%"))
    return results


def _kv_quant_gather_section(quick: bool) -> list:
    """Per-step cost of dequant-in-gather paged attention
    (ops/kv_quant.py + ops/attention.py): the same decode-shaped
    block-table attention as `_paged_gather_section`, read (a) from a
    dense f32 pool and (b) from an int8 pool with per-block scales
    dequantized INSIDE the gather. The delta is the pure price of the
    widening multiply the quantized plane pays per decode step — buying
    ~2x pool blocks per HBM byte (bench.py `kv_quant` section reports
    the concurrency side). Runs anywhere: both lower to the same XLA
    reference einsums off-TPU, so the dequant overhead measured is the
    real added op count, not a kernel artifact."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import paged_attention
    from ray_tpu.ops.kv_quant import (block_scale, quantize,
                                      resolve_kv_quant)

    B, H, KV, D, T = 8, 4, 2, 16, 16
    spans = (256,) if quick else (256, 1024)
    qspec = resolve_kv_quant("int8")
    results = []
    for span in spans:
        MB = span // T
        NB = 4 * MB + 1
        key = jax.random.PRNGKey(span)
        q = jax.random.normal(key, (B, 1, H, D), jnp.float32)
        pool_k = jax.random.normal(key, (NB, T, KV, D), jnp.float32)
        pool_v = pool_k + 1.0
        amax_k = jnp.max(jnp.abs(pool_k), axis=(1, 3))
        amax_v = jnp.max(jnp.abs(pool_v), axis=(1, 3))
        sk = block_scale(amax_k, qspec)
        sv = block_scale(amax_v, qspec)
        qk = quantize(pool_k, sk[:, None, :, None], qspec)
        qv = quantize(pool_v, sv[:, None, :, None], qspec)
        bt = (1 + (jnp.arange(B * MB) * 7) % (NB - 1)).reshape(B, MB)
        bt = bt.astype(jnp.int32)
        slots = jnp.full((B, 1), span - 1, jnp.int32)

        dense_fn = jax.jit(lambda q, k, v: paged_attention(
            q, k, v, bt, slots, kv_valid_len=span))
        quant_fn = jax.jit(lambda q, k, v, sk, sv: paged_attention(
            q, k, v, bt, slots, kv_valid_len=span, k_scale=sk,
            v_scale=sv))
        dense_fn(q, pool_k, pool_v).block_until_ready()
        quant_fn(q, qk, qv, sk, sv).block_until_ready()

        def run(fn, *args):
            ts = []
            for _ in range(TRIALS):
                t0 = time.perf_counter()
                for _ in range(20):
                    out = fn(*args)
                out.block_until_ready()
                ts.append((time.perf_counter() - t0) / 20 * 1000)
            return statistics.median(ts)

        d_ms = run(dense_fn, q, pool_k, pool_v)
        z_ms = run(quant_fn, q, qk, qv, sk, sv)
        results.append((f"paged_attention_dense_gather_ms_s{span}",
                        d_ms, "ms"))
        results.append((f"paged_attention_dequant_gather_ms_s{span}",
                        z_ms, "ms"))
        results.append((f"paged_attention_dequant_overhead_pct_s{span}",
                        (z_ms - d_ms) / d_ms * 100.0 if d_ms else 0.0,
                        "%"))
    return results


def _handoff_section(quick: bool) -> list:
    """Disaggregated handoff seam cost (models/engine.py
    `export_request` / `import_request` — the spill a prefill-class
    replica pays per finished prefill and the re-admission a
    decode-class replica pays per import): per prompt span, the wall
    ms to EXPORT (pow2-padded block gather + device->host pull + host
    staging), to IMPORT (re-submit + planting the paged swap pre-seed;
    no device work), and to ADMIT (the first decode step after the
    import: host->device scatter + decode dispatch), plus the payload
    bytes per request — dense f32 KV vs int8-quantized blocks. The
    quant plane moves ~4x fewer KV bytes (per-block scale rows ride
    along), which is the handoff-bandwidth side of the kv_quant
    trade. Runs anywhere: the staging copies and op counts are
    host-side and real on any backend."""
    import jax
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine

    spans = (128,) if quick else (128, 512, 2048)
    cfg = LlamaConfig.nano(max_seq_len=max(spans) + 64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(17)
    results = []
    for span in spans:
        prompt = rng.randint(1, cfg.vocab_size, size=span).tolist()
        max_len = span + 16
        for quant in (None, "int8"):
            def make(name):
                return DecodeEngine(params, cfg, batch_slots=1,
                                    max_len=max_len, paged=True,
                                    kv_block_tokens=16,
                                    kv_quant=quant, engine_id=name)

            pre = make(f"hb-pre-{span}-{quant}")
            pre.prefill_only = True
            dec = make(f"hb-dec-{span}-{quant}")
            ex, im, ad = [], [], []

            def cycle(timed):
                rid = pre.submit(prompt, 4)
                while not pre.handoff_ready():
                    pre.step()
                t0 = time.perf_counter()
                h = pre.export_request(rid)
                t1 = time.perf_counter()
                dec.import_request(h)
                t2 = time.perf_counter()
                dec.step()          # admission: swap-in scatter
                t3 = time.perf_counter()
                dec.run()           # drain so the next cycle is clean
                if timed:
                    ex.append((t1 - t0) * 1000)
                    im.append((t2 - t1) * 1000)
                    ad.append((t3 - t2) * 1000)

            cycle(False)            # compile gather/scatter programs
            for _ in range(TRIALS):
                cycle(True)
            tag = "_int8" if quant else ""
            per_req_bytes = pre.handoff_out_bytes / (TRIALS + 1)
            results.append((f"handoff_export_ms_s{span}{tag}",
                            statistics.median(ex), "ms"))
            results.append((f"handoff_import_ms_s{span}{tag}",
                            statistics.median(im), "ms"))
            results.append((f"handoff_admit_ms_s{span}{tag}",
                            statistics.median(ad), "ms"))
            results.append((f"handoff_bytes_s{span}{tag}",
                            per_req_bytes, "bytes"))
    return results


def _fleet_router_section(quick: bool) -> list:
    """Per-decision cost of the fleet routers (models/fleet.py): the
    wall microseconds one `submit()` spends choosing a replica, per
    fleet size. The pow-2 + affinity router probes EVERY replica's
    prefix trie and stats plane per decision (peek-only host walks,
    zero device work), so its cost must stay trivially small next to
    a single prefill — this section is the guard. Round-robin is the
    floor (an index increment)."""
    import jax
    import numpy as np

    from ray_tpu.models import LLMFleet, LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine
    from ray_tpu.models.fleet import (PowerOfTwoAffinityRouter,
                                      RoundRobinRouter)

    cfg = LlamaConfig.nano(max_seq_len=256)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(3)
    sizes = (4,) if quick else (2, 4, 8)
    n_decisions = 50 if quick else 200
    prompt = rng.randint(1, cfg.vocab_size, size=96).tolist()

    results = []
    for n in sizes:
        for router_name, router in (
                ("round_robin", RoundRobinRouter()),
                ("pow2_affinity", PowerOfTwoAffinityRouter())):
            def factory(name):
                return DecodeEngine(params, cfg, batch_slots=2,
                                    max_len=cfg.max_seq_len,
                                    prefix_cache=True, prefix_block=16,
                                    enable_metrics=False)
            fleet = LLMFleet(factory, initial_replicas=n,
                             router=router,
                             fleet_id=f"mb-{router_name}-{n}")
            # Seed one replica's trie so the affinity probe walks a
            # non-trivial index (the expensive honest case).
            fleet.submit(prompt, 2)
            fleet.run()
            running = fleet._running()
            t0 = time.perf_counter()
            for _ in range(n_decisions):
                router.choose(running, prompt)
            us = (time.perf_counter() - t0) / n_decisions * 1e6
            results.append((
                f"fleet_router_{router_name}_decision_us_n{n}",
                us, "us"))
    return results


def _tracer_overhead_section(quick: bool) -> list:
    """Cost of the request-lifecycle tracer (models/engine_trace.py):
    raw event-emit throughput, and the engine-level tax — wall time of
    an identical decode churn with tracing OFF (the NullEngineTracer
    default), with the ring tracer ON, and the on/off overhead
    fraction. The zero-cost-when-off claim is the one that matters
    (every call site guards on `trace.enabled` before building args),
    so off-vs-baseline must be noise; on-vs-off bounds what turning a
    production engine's tracing on costs per token."""
    import jax
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine
    from ray_tpu.models.engine_trace import EngineTracer

    # Raw primitive cost: one span via the mark frontier (the decode
    # hot path's shape: span_since_mark with a small args dict).
    tracer = EngineTracer(capacity=1 << 14)
    n_ev = 20_000 if quick else 100_000
    tracer.mark(0)

    def emit():
        for _ in range(n_ev):
            tracer.span_since_mark("decode_block", 0,
                                   {"tokens": 1, "horizon": 8})

    results = [("tracer_span_emit_per_second",
                timed_median(emit, n_ev), "events/s")]

    cfg = LlamaConfig.nano(max_seq_len=256)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size, size=24).tolist()
               for _ in range(8)]
    new_tokens = 8 if quick else 32

    def churn(trace):
        eng = DecodeEngine(params, cfg, batch_slots=4,
                           max_len=cfg.max_seq_len,
                           enable_metrics=False, trace=trace)
        for p in prompts:
            eng.submit(p, new_tokens)
        eng.run()         # compile warmup
        for p in prompts:
            eng.submit(p, new_tokens)
        t0 = time.perf_counter()
        eng.run()
        return time.perf_counter() - t0

    churn(False)          # shared jit cache warm
    n_tok = len(prompts) * new_tokens
    off = statistics.median([churn(False) for _ in range(TRIALS)])
    on = statistics.median([churn(True) for _ in range(TRIALS)])
    results.append(("tracer_off_decode_us_per_token",
                    off / n_tok * 1e6, "us"))
    results.append(("tracer_on_decode_us_per_token",
                    on / n_tok * 1e6, "us"))
    results.append(("tracer_overhead_frac",
                    (on - off) / off if off else 0.0, "frac"))
    return results


def _state_snapshot_section(quick: bool) -> list:
    """Cost of one serving state snapshot (util/state/serving.py) and
    one metrics-history sample (util/metrics_history.py) against a
    BUSY engine — queue + live slots + mid-prefill rows, the state a
    status poller actually reads. Calls/s for each query plus the
    per-poll microseconds of the full status-CLI read set; these are
    the numbers behind bench.py's `state_snapshot_overhead_frac`."""
    import gc

    import jax
    import numpy as np

    from ray_tpu.models import LlamaConfig, llama_init
    from ray_tpu.models.engine import DecodeEngine
    from ray_tpu.util import metrics_history as mh
    from ray_tpu.util.state import serving

    gc.collect()                  # drop corpses from earlier sections
    cfg = LlamaConfig.nano(max_seq_len=256)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(7)
    eng = DecodeEngine(params, cfg, batch_slots=4,
                       max_len=cfg.max_seq_len, prefix_cache=True,
                       prefix_block=16)
    for _ in range(12):           # oversubscribed: queue stays deep
        eng.submit(rng.randint(1, cfg.vocab_size, size=24).tolist(),
                   64)
    eng.step()                    # live slots + queue, mid-churn

    n = 2_000 if quick else 10_000
    results = []
    for name, fn in [
        ("state_list_engines_per_second", serving.list_engines),
        ("state_list_requests_per_second", serving.list_requests),
        ("state_summarize_fleet_per_second", serving.summarize_fleet),
        ("metrics_history_sample_per_second",
         lambda: mh.sample_now(force=True)),
    ]:
        fn()                      # warm lazy paths outside the window
        results.append((name, timed_median(
            lambda: [fn() for _ in range(n)], n), "calls/s"))

    def poll():
        serving.summarize_fleet()
        mh.sample_now(force=True)

    rate = timed_median(lambda: [poll() for _ in range(n)], n)
    results.append(("state_full_poll_us", 1e6 / rate if rate else 0.0,
                    "us"))
    eng.run()
    return results


def _graft_lint_section(quick: bool) -> list:
    """Wall time of one full graftlint sweep (all eight analyzers,
    interprocedural summaries included, over the serving tree — the same
    work `test_graft_lint.py::test_tree_is_clean` does in tier-1 CI).
    Budget: < 4 s full-tree, so the gate stays cheap enough to run on
    every commit; also reports per-file microseconds and the open finding
    count (must be 0 — bench.py tracks it as `lint_violations_total`)."""
    from ray_tpu._private.lint import lint_paths

    paths = ["ray_tpu/models", "ray_tpu/serve", "ray_tpu/util"]
    lint_paths(paths)                       # warm import + glossary cache
    trials = 1 if quick else TRIALS
    times = []
    report = None
    for _ in range(trials):
        t0 = time.perf_counter()
        report = lint_paths(paths)
        times.append(time.perf_counter() - t0)
    sweep = statistics.median(times)
    return [
        ("lint_sweep_seconds", sweep, "s"),
        ("lint_us_per_file",
         sweep / max(report.files_scanned, 1) * 1e6, "us"),
        ("lint_violations_total", float(len(report.open)), "count"),
    ]


def main(quick: bool = False):
    import numpy as np

    import ray_tpu

    scale = 0.1 if quick else 1.0
    # Print the serving-engine sections immediately: their numbers must
    # survive an environment-specific failure in a later section.
    for name, value, unit in _graft_lint_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _decode_dispatch_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _spec_dispatch_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _sharded_dispatch_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _dispatch_gap_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _prefix_admission_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _paged_gather_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _kv_quant_gather_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _handoff_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _fleet_router_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _tracer_overhead_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    for name, value, unit in _state_snapshot_section(quick):
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}), flush=True)
    results = []
    ray_tpu.init(num_cpus=4)

    # --- trivial task throughput (pipelined) ---
    @ray_tpu.remote
    def noop():
        return None

    n = int(3000 * scale)
    # Warm workers, leases, the fastlane channel, and the inline-exec
    # observation window; let store pre-population settle.
    ray_tpu.get([noop.remote() for _ in range(300)])
    time.sleep(1.0)

    def tasks():
        ray_tpu.get([noop.remote() for _ in range(n)])

    results.append(("tasks_per_second", timed_median(tasks, n), "tasks/s"))

    # --- single actor call latency / throughput ---
    @ray_tpu.remote
    class A:
        def m(self, x=None):
            return x

    a = A.remote()
    for _ in range(20):  # warm conn + fastlane channel
        ray_tpu.get(a.m.remote())
    n = int(2000 * scale)

    def actor_sync():
        for _ in range(n):
            ray_tpu.get(a.m.remote())

    rate = timed_median(actor_sync, n)
    results.append(("actor_calls_sync_per_second", rate, "calls/s"))
    results.append(("actor_call_latency_ms", 1000.0 / rate, "ms"))

    def actor_async():
        ray_tpu.get([a.m.remote() for _ in range(n)])

    results.append(("actor_calls_pipelined_per_second",
                    timed_median(actor_async, n), "calls/s"))

    # --- object store bandwidth (zero-copy numpy) ---
    mb = 64 if quick else 256
    arr = np.random.rand(mb * 1024 * 1024 // 8)

    put_rates, get_rates = [], []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        ref = ray_tpu.put(arr)
        put_rates.append(mb / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        out = ray_tpu.get(ref)
        get_rates.append(mb / (time.perf_counter() - t0))
        assert out.shape == arr.shape
        del out, ref
    results.append(("object_store_put_mb_per_second",
                    statistics.median(put_rates), "MiB/s"))
    results.append(("object_store_get_mb_per_second",
                    statistics.median(get_rates), "MiB/s"))

    # --- many small objects in one get ---
    n = int(1000 * scale)
    refs = [ray_tpu.put(i) for i in range(n)]

    def many_get():
        ray_tpu.get(refs)

    results.append(("small_objects_get_per_second",
                    timed_median(many_get, n), "objects/s"))

    # --- actor creation storm (warm pool) ---
    # Reference envelope row: actor creation throughput (BASELINE.md
    # 40k-actor scale / release scalability suite). A fresh cluster
    # sized to the storm keeps the prestart pool warm for all N, so the
    # metric isolates the creation pipeline (pipelined GCS registration
    # + lease + creation push + first call), not process cold start.
    ray_tpu.shutdown()
    storm_n = 4 if quick else 16
    ray_tpu.init(num_cpus=storm_n)

    @ray_tpu.remote
    class S:
        def m(self, x=None):
            return x

    time.sleep(2.0 if quick else 8.0)  # prestart pool fill

    storms = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        batch = [S.remote() for _ in range(storm_n)]
        ray_tpu.get([b.m.remote(1) for b in batch], timeout=120)
        storms.append(storm_n / (time.perf_counter() - t0))
        for b in batch:
            ray_tpu.kill(b)
        time.sleep(1.0 if quick else 4.0)  # pool refill between trials
    results.append(("actor_creation_storm_per_second",
                    statistics.median(storms), "actors/s"))

    for name, value, unit in results:
        print(json.dumps({"metric": name, "value": round(value, 4),
                          "unit": unit}))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main(quick="--quick" in sys.argv)
