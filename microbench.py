"""Core-runtime microbenchmarks (reference: python/ray/_private/ray_perf.py
+ release/microbenchmark/): task throughput, actor call latency, object
store put/get bandwidth. Prints one JSON line per metric.

Each metric is measured over several trials and reported as the MEDIAN:
this box runs co-tenant load (round-3 verdict: a single capture swung 2x
under background activity), so single-shot numbers are noise.

Run: python microbench.py [--quick]
"""

import json
import os
import statistics
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)

TRIALS = 3


def timed_median(fn, n, trials=TRIALS):
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fn()
        rates.append(n / (time.perf_counter() - t0))
    return statistics.median(rates)


def main(quick: bool = False):
    import numpy as np

    import ray_tpu

    scale = 0.1 if quick else 1.0
    ray_tpu.init(num_cpus=4)
    results = []

    # --- trivial task throughput (pipelined) ---
    @ray_tpu.remote
    def noop():
        return None

    n = int(3000 * scale)
    # Warm workers, leases, the fastlane channel, and the inline-exec
    # observation window; let store pre-population settle.
    ray_tpu.get([noop.remote() for _ in range(300)])
    time.sleep(1.0)

    def tasks():
        ray_tpu.get([noop.remote() for _ in range(n)])

    results.append(("tasks_per_second", timed_median(tasks, n), "tasks/s"))

    # --- single actor call latency / throughput ---
    @ray_tpu.remote
    class A:
        def m(self, x=None):
            return x

    a = A.remote()
    for _ in range(20):  # warm conn + fastlane channel
        ray_tpu.get(a.m.remote())
    n = int(2000 * scale)

    def actor_sync():
        for _ in range(n):
            ray_tpu.get(a.m.remote())

    rate = timed_median(actor_sync, n)
    results.append(("actor_calls_sync_per_second", rate, "calls/s"))
    results.append(("actor_call_latency_ms", 1000.0 / rate, "ms"))

    def actor_async():
        ray_tpu.get([a.m.remote() for _ in range(n)])

    results.append(("actor_calls_pipelined_per_second",
                    timed_median(actor_async, n), "calls/s"))

    # --- object store bandwidth (zero-copy numpy) ---
    mb = 64 if quick else 256
    arr = np.random.rand(mb * 1024 * 1024 // 8)

    put_rates, get_rates = [], []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        ref = ray_tpu.put(arr)
        put_rates.append(mb / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        out = ray_tpu.get(ref)
        get_rates.append(mb / (time.perf_counter() - t0))
        assert out.shape == arr.shape
        del out, ref
    results.append(("object_store_put_mb_per_second",
                    statistics.median(put_rates), "MiB/s"))
    results.append(("object_store_get_mb_per_second",
                    statistics.median(get_rates), "MiB/s"))

    # --- many small objects in one get ---
    n = int(1000 * scale)
    refs = [ray_tpu.put(i) for i in range(n)]

    def many_get():
        ray_tpu.get(refs)

    results.append(("small_objects_get_per_second",
                    timed_median(many_get, n), "objects/s"))

    # --- actor creation storm (warm pool) ---
    # Reference envelope row: actor creation throughput (BASELINE.md
    # 40k-actor scale / release scalability suite). A fresh cluster
    # sized to the storm keeps the prestart pool warm for all N, so the
    # metric isolates the creation pipeline (pipelined GCS registration
    # + lease + creation push + first call), not process cold start.
    ray_tpu.shutdown()
    storm_n = 4 if quick else 16
    ray_tpu.init(num_cpus=storm_n)

    @ray_tpu.remote
    class S:
        def m(self, x=None):
            return x

    time.sleep(2.0 if quick else 8.0)  # prestart pool fill

    storms = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        batch = [S.remote() for _ in range(storm_n)]
        ray_tpu.get([b.m.remote(1) for b in batch], timeout=120)
        storms.append(storm_n / (time.perf_counter() - t0))
        for b in batch:
            ray_tpu.kill(b)
        time.sleep(1.0 if quick else 4.0)  # pool refill between trials
    results.append(("actor_creation_storm_per_second",
                    statistics.median(storms), "actors/s"))

    for name, value, unit in results:
        print(json.dumps({"metric": name, "value": round(value, 2),
                          "unit": unit}))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main(quick="--quick" in sys.argv)
